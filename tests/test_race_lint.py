"""mxrace: the static concurrency gate over the threaded host tiers
(mxnet_tpu/analysis/race_lint.py; docs/concurrency.md).

Covers the five RACE rules each with a broken-fixture subprocess test
exiting rc=2 through the real CLI (the mutation-seam discipline), the
PR-6 historical ``_key_owner`` fixture (the analyzer must catch the
repo's own shipped bug), the lock-order/hierarchy sync both ways, the
interprocedural refinements (``*_locked`` helpers, lambdas, init-only
setup methods), the whole-repo sweep staying clean, race-report
byte-determinism, the schema-5 ``race`` section through
``tools/parse_log.py``, and a pre-fix fixture for every real
concurrency finding this gate surfaced in shipped code.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.analysis

from mxnet_tpu.analysis import race_lint as rl
from mxnet_tpu.analysis.findings import RULES, ERROR

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HIERARCHY = os.path.join(REPO, "docs", "concurrency.md")


def rules(findings):
    return sorted({f.rule_id for f in findings})


def _lint(body):
    return rl.lint_race_source(textwrap.dedent(body), filename="fix.py")


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-m", "mxnet_tpu.analysis"]
                          + list(args), capture_output=True, text=True,
                          cwd=REPO, env=env, timeout=300)


def _cli_fixture(tmp_path, body):
    """Race-lint a broken fixture through the real CLI."""
    script = tmp_path / "fixture.py"
    script.write_text(textwrap.dedent(body))
    return _run_cli("--race", str(script))


# ---------------------------------------------------------------------------
# rule registration
# ---------------------------------------------------------------------------
def test_race_rules_registered_as_errors():
    for rule in ("RACE001", "RACE002", "RACE003", "RACE004", "RACE005"):
        assert rule in RULES
        assert RULES[rule][0] == ERROR


# ---------------------------------------------------------------------------
# RACE001: lock-guard inference
# ---------------------------------------------------------------------------
PR6_KEY_OWNER = """\
    import threading

    class PSServerFixture:
        def __init__(self):
            self._live_lock = threading.Lock()
            self._key_owner = {}

        def assign(self, key, rank):
            with self._live_lock:
                self._key_owner[key] = rank

        def on_rank_dead(self, dead_rank, live):
            # the PR-6 shipped bug: iterating the ownership dict BARE
            # while assign() mutates it under the lock
            moved = []
            for key, rank in self._key_owner.items():
                if rank == dead_rank:
                    moved.append(key)
            return moved
"""


def test_race001_flags_the_pr6_key_owner_bug():
    findings = _lint(PR6_KEY_OWNER)
    assert "RACE001" in rules(findings)
    hit = [f for f in findings if f.rule_id == "RACE001"]
    assert any("_key_owner" in f.message for f in hit)
    assert any("PSServerFixture" in f.message for f in hit)


def test_race001_pr6_fixture_exits_2_through_cli(tmp_path):
    proc = _cli_fixture(tmp_path, PR6_KEY_OWNER)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "RACE001" in proc.stdout and "_key_owner" in proc.stdout


def test_race001_clean_when_every_access_is_locked():
    findings = _lint("""\
        import threading

        class Guarded:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def snapshot(self):
                with self._lock:
                    return list(self._items)
    """)
    assert findings == []


def test_race001_inconsistent_lock_sets():
    findings = _lint("""\
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._n = 0

            def inc_a(self):
                with self._a:
                    self._n += 1

            def inc_b(self):
                with self._b:
                    self._n += 1
    """)
    hit = [f for f in findings if f.rule_id == "RACE001"]
    assert len(hit) == 1 and "inconsistent lock sets" in hit[0].message


def test_race001_locked_helper_inherits_callers_held_set():
    # the *_locked convention: the private helper is only ever called
    # under the lock, so its bare-looking writes are guarded
    findings = _lint("""\
        import threading

        class Conv:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}

            def apply(self, k, v):
                with self._lock:
                    self._apply_locked(k, v)

            def _apply_locked(self, k, v):
                self._state[k] = v

            def get(self, k):
                with self._lock:
                    return self._state.get(k)
    """)
    assert findings == []


def test_race001_lambda_inherits_held_set():
    # cv.wait_for predicates run holding the condition — no finding
    findings = _lint("""\
        import threading

        class Pending:
            def __init__(self):
                self._cv = threading.Condition()
                self._pending = set()

            def claim(self, key):
                with self._cv:
                    self._pending.add(key)

            def await_done(self, key):
                with self._cv:
                    self._cv.wait_for(lambda: key not in self._pending)
    """)
    assert findings == []


def test_race001_closure_does_not_inherit_held_set():
    # a def closure is a thread target: bare accesses inside it are
    # NOT blessed by the spawning method's held locks
    findings = _lint("""\
        import threading

        class Spawner:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def start(self):
                def run():
                    while self._n < 10:
                        pass
                t = threading.Thread(target=run, daemon=True)
                t.start()
    """)
    assert "RACE001" in rules(findings)


def test_race001_init_only_helper_shares_init_exemption():
    # _recover runs before any thread exists (only __init__ calls it):
    # its bare writes neither violate nor weaken the runtime guard
    findings = _lint("""\
        import threading

        class Server:
            def __init__(self, path):
                self._lock = threading.Lock()
                self._store = {}
                self._recover(path)

            def _recover(self, path):
                self._store["seed"] = path

            def apply(self, k, v):
                with self._lock:
                    self._store[k] = v

            def pull(self, k):
                with self._lock:
                    return self._store[k]
    """)
    assert findings == []


def test_race001_disable_comment_suppresses():
    findings = _lint("""\
        import threading

        class Deliberate:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def peek(self):
                return self._n  # mxlint: disable=RACE001
    """)
    assert findings == []


# ---------------------------------------------------------------------------
# RACE002: lock-order cycles + the pinned hierarchy
# ---------------------------------------------------------------------------
RACE002_CYCLE = """\
    import threading

    class ABBA:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
"""


def test_race002_flags_lock_order_cycle():
    findings = _lint(RACE002_CYCLE)
    hit = [f for f in findings if f.rule_id == "RACE002"]
    assert hit and "deadlock" in hit[0].message


def test_race002_cycle_fixture_exits_2_through_cli(tmp_path):
    proc = _cli_fixture(tmp_path, RACE002_CYCLE)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "RACE002" in proc.stdout


def test_race002_hierarchy_sync_both_ways(tmp_path):
    doc = tmp_path / "concurrency.md"
    doc.write_text(textwrap.dedent("""\
        | # | outer | inner | why |
        |---|-------|-------|-----|
        | 1 | `A._x` | `A._y` | pinned |
        | 2 | `A._stale` | `A._gone` | no longer observed |
    """))
    edges = [("A._x", "A._y", "m.py:3"),
             ("A._y", "A._z", "m.py:9")]
    findings = rl.lock_order_findings(edges, hierarchy_path=str(doc))
    msgs = [f.message for f in findings if f.rule_id == "RACE002"]
    assert len(msgs) == 2
    assert any("A._y -> A._z" in m and "not pinned" in m for m in msgs)
    assert any("A._stale -> A._gone" in m and "no longer observed" in m
               for m in msgs)


def test_pinned_hierarchy_matches_observed_edges_exactly():
    """The checked-in docs/concurrency.md table IS the observed edge
    set — the sync that RACE002 enforces, asserted directly."""
    pinned = set(rl.parse_hierarchy(HIERARCHY))
    summary = rl.race_summary()
    observed = {(e["outer"], e["inner"]) for e in summary["edges"]}
    assert pinned == observed
    assert len(pinned) >= 5


# ---------------------------------------------------------------------------
# RACE003: blocking under a held lock
# ---------------------------------------------------------------------------
RACE003_BLOCKING = """\
    import queue
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()

        def take(self):
            with self._lock:
                return self._q.get()
"""


def test_race003_flags_unbounded_get_under_lock():
    findings = _lint(RACE003_BLOCKING)
    hit = [f for f in findings if f.rule_id == "RACE003"]
    assert hit and ".get()" in hit[0].message


def test_race003_fixture_exits_2_through_cli(tmp_path):
    proc = _cli_fixture(tmp_path, RACE003_BLOCKING)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "RACE003" in proc.stdout


def test_race003_timeout_and_wait_on_own_cv_are_clean():
    findings = _lint("""\
        import queue
        import threading

        class Pump:
            def __init__(self):
                self._cv = threading.Condition()
                self._q = queue.Queue()

            def take(self):
                with self._cv:
                    return self._q.get(timeout=0.2)

            def park(self):
                with self._cv:
                    self._cv.wait()
    """)
    assert findings == []


def test_race003_wait_on_foreign_cv_is_flagged():
    findings = _lint("""\
        import threading

        class Cross:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def park(self):
                with self._lock:
                    with self._cv:
                        self._cv.wait()
    """)
    # _cv.wait() releases _cv but NOT the outer _lock
    hit = [f for f in findings if f.rule_id == "RACE003"]
    assert hit and ".wait()" in hit[0].message


def test_race003_flags_sleep_and_maybe_inject_under_lock():
    findings = _lint("""\
        import threading
        import time
        from mxnet_tpu.resilience import chaos

        class Slow:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    chaos.maybe_inject("site")
                    time.sleep(0.1)
    """)
    hit = [f.message for f in findings if f.rule_id == "RACE003"]
    assert len(hit) == 2
    assert any("maybe_inject" in m for m in hit)
    assert any("sleep" in m for m in hit)


# ---------------------------------------------------------------------------
# RACE004: thread lifecycle
# ---------------------------------------------------------------------------
RACE004_LEAK = """\
    import threading

    def start_worker(fn):
        t = threading.Thread(target=fn)
        t.start()
        return t
"""


def test_race004_flags_non_daemon_never_joined_thread():
    findings = _lint(RACE004_LEAK)
    assert rules(findings) == ["RACE004"]


def test_race004_fixture_exits_2_through_cli(tmp_path):
    proc = _cli_fixture(tmp_path, RACE004_LEAK)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "RACE004" in proc.stdout


def test_race004_daemon_or_joined_is_clean():
    findings = _lint("""\
        import threading

        class Owner:
            def __init__(self, fn):
                self._t = threading.Thread(target=fn, daemon=True)
                self._t.start()
                self._u = threading.Thread(target=fn)
                self._u.start()

            def stop(self):
                self._u.join()
    """)
    assert findings == []


# ---------------------------------------------------------------------------
# RACE005: callbacks under the owner's lock
# ---------------------------------------------------------------------------
RACE005_WATCHDOG = """\
    import threading

    class Watchdog:
        def __init__(self, on_dead):
            self._lock = threading.Lock()
            self._on_dead = on_dead
            self._dead = set()

        def check(self, rank):
            with self._lock:
                self._dead.add(rank)
                self._on_dead(rank)
"""


def test_race005_flags_callback_invoked_under_lock():
    findings = _lint(RACE005_WATCHDOG)
    hit = [f for f in findings if f.rule_id == "RACE005"]
    assert hit and "_on_dead" in hit[0].message


def test_race005_fixture_exits_2_through_cli(tmp_path):
    proc = _cli_fixture(tmp_path, RACE005_WATCHDOG)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "RACE005" in proc.stdout


def test_race005_copy_then_call_outside_is_clean():
    # the PR-6 watchdog FIX: snapshot under the lock, call outside
    findings = _lint("""\
        import threading

        class Watchdog:
            def __init__(self, on_dead):
                self._lock = threading.Lock()
                self._on_dead = on_dead
                self._dead = set()

            def check(self, rank):
                with self._lock:
                    self._dead.add(rank)
                self._on_dead(rank)
    """)
    assert findings == []


def test_race005_loop_over_callback_collection_under_lock():
    findings = _lint("""\
        import threading

        class Bus:
            def __init__(self):
                self._lock = threading.Lock()
                self._callbacks = []

            def subscribe(self, cb):
                with self._lock:
                    self._callbacks.append(cb)

            def publish(self, evt):
                with self._lock:
                    for cb in self._callbacks:
                        cb(evt)
    """)
    assert "RACE005" in rules(findings)


# ---------------------------------------------------------------------------
# pre-fix fixtures: the real findings this gate surfaced in shipped code
# ---------------------------------------------------------------------------
PREFIX_FIXTURES = {
    # serving/batcher.py queue_depth read len(self._heap) bare while
    # submit() mutates the heap under _cond
    "batcher_queue_depth": ("_heap", """\
        import threading

        class Batcher:
            def __init__(self):
                self._cond = threading.Condition()
                self._heap = []

            def submit(self, r):
                with self._cond:
                    self._heap.append(r)

            def queue_depth(self):
                return len(self._heap)
    """),
    # serving/batcher.py _run_batch picked the bucket from a BARE
    # self.runner read before taking the runner lock for the forward
    "batcher_runner_swap": ("runner", """\
        import threading

        class Batcher:
            def __init__(self, runner):
                self._runner_lock = threading.Lock()
                self.runner = runner

            def run_batch(self, n, x):
                bucket = self.runner.bucket_for(n)
                with self._runner_lock:
                    return self.runner.forward_batch(x), bucket

            def swap_runner(self, runner):
                with self._runner_lock:
                    old, self.runner = self.runner, runner
                return old
    """),
    # kvstore_ps.py _metrics_samples read the WAL counters bare while
    # the apply path mutates them under _state_lock
    "ps_metrics_counters": ("_wal_seq", """\
        import threading

        class PSServer:
            def __init__(self):
                self._state_lock = threading.Lock()
                self._wal_seq = 0

            def wal_append(self, rec):
                with self._state_lock:
                    self._wal_seq += 1

            def metrics_samples(self):
                return [("mxtpu_ps_wal_seq", {}, self._wal_seq)]
    """),
    # kvstore_ps.py heartbeat reply computed the dead-set union AFTER
    # releasing _live_lock
    "ps_dead_ranks_union": ("_dead_ranks", """\
        import threading

        class PSServer:
            def __init__(self):
                self._live_lock = threading.Lock()
                self._dead_ranks = set()

            def mark_dead(self, rank):
                with self._live_lock:
                    self._dead_ranks.add(rank)

            def beat(self, rank, monitor_dead):
                with self._live_lock:
                    self._dead_ranks.discard(rank)
                return len(monitor_dead | self._dead_ranks)
    """),
    # kvstore_ps.py PSClient._transfer_epoch read (reconnects,
    # failovers) bare while _reconnect bumps them under _lock
    "ps_client_epoch": ("reconnects", """\
        import threading

        class PSClient:
            def __init__(self):
                self._lock = threading.Lock()
                self.reconnects = 0

            def _reconnect(self):
                with self._lock:
                    self.reconnects += 1

            def transfer_epoch(self):
                return self.reconnects
    """),
    # serving/fleet.py CanarySplit/ModelFleet properties + __repr__
    # read ramp/default state bare while advance()/register() mutate
    # it under _lock
    "fleet_bare_properties": ("_stage", """\
        import threading

        class CanarySplit:
            def __init__(self, schedule):
                self._lock = threading.Lock()
                self.schedule = schedule
                self._stage = 0

            def advance(self):
                with self._lock:
                    self._stage += 1
                    return self.schedule[self._stage]

            def fraction(self):
                return self.schedule[self._stage]
    """),
    # io DeviceFeedIter.live_slots_max read the high-water mark bare
    # while the worker updates it under _live_lock
    "io_live_slots_max": ("_live_max", """\
        import threading

        class DeviceFeedIter:
            def __init__(self):
                self._live_lock = threading.Lock()
                self._live = 0
                self._live_max = 0

            def on_batch(self):
                with self._live_lock:
                    self._live += 1
                    self._live_max = max(self._live_max, self._live)

            def live_slots_max(self):
                return self._live_max
    """),
    # telemetry/flight.py set_cursor stored through self._mm bare —
    # close() can invalidate the mmap mid-store
    "flight_set_cursor": ("_mm", """\
        import threading

        class FlightRecorder:
            def __init__(self, mm):
                self._lock = threading.Lock()
                self._mm = mm
                self._closed = False

            def set_cursor(self, step):
                self._mm[0:8] = step

            def close(self):
                with self._lock:
                    self._closed = True
                    self._mm.close()
                    self._mm = None
    """),
    # telemetry/attribution.py on_step appended the closed window bare
    # while flush_window drains under _lock on the scrape thread
    "attribution_on_step": ("_pending", """\
        import threading

        class StepAttribution:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []

            def on_step(self, step, dt, cur):
                self._pending.append((step, dt, cur))

            def flush_window(self):
                with self._lock:
                    drained, self._pending = self._pending, []
                return drained
    """),
}


@pytest.mark.parametrize("name", sorted(PREFIX_FIXTURES))
def test_prefix_fixture_is_flagged_race001(name):
    attr, body = PREFIX_FIXTURES[name]
    findings = _lint(body)
    hit = [f for f in findings if f.rule_id == "RACE001"]
    assert hit, "pre-fix pattern %r no longer flagged" % name
    assert any("'%s'" % attr in f.message for f in hit), \
        "expected %r named in %s" % (attr, [str(f) for f in hit])


# ---------------------------------------------------------------------------
# the whole-repo sweep
# ---------------------------------------------------------------------------
def test_threaded_targets_cover_the_host_tiers():
    targets = rl.threaded_targets()
    assert "mxnet_tpu/kvstore_ps.py" in targets
    assert "mxnet_tpu/engine.py" in targets
    assert any(t.startswith("mxnet_tpu/serving/") for t in targets)
    assert any(t.startswith("mxnet_tpu/resilience/") for t in targets)
    assert any(t.startswith("mxnet_tpu/io/") for t in targets)
    assert any(t.startswith("mxnet_tpu/telemetry/") for t in targets)
    assert any(t.startswith("mxnet_tpu/mlops/") for t in targets)
    assert any(t.startswith("tools/") for t in targets)
    assert targets == sorted(targets)


def test_sweep_is_clean_and_deterministic():
    """The shipped threaded tiers race-lint clean (fixes landed,
    deliberate exceptions disabled inline), and two sweeps agree."""
    findings = rl.lint_threaded_sources()
    assert findings == [], "\n".join(str(f) for f in findings)
    assert rl.race_summary() == rl.race_summary()


def test_race_sweep_report_byte_identical_across_cli_runs():
    a = _run_cli("--race", "--json")
    b = _run_cli("--race", "--json")
    assert a.returncode == 0, a.stdout + a.stderr
    assert a.stdout == b.stdout


def test_race_summary_shape():
    s = rl.race_summary()
    assert s["n_files"] >= 40
    assert "PSServer._state_lock" in s["locks"]
    assert "PSServer._key_lock()" in s["locks"]
    assert s["guards"]["Batcher._heap"] == ["Batcher._cond"]
    assert s["guards"]["PSServer._key_owner"] == ["PSServer._live_lock"]
    for edge in s["edges"]:
        assert set(edge) == {"outer", "inner", "site"}
    assert s["locks"] == sorted(s["locks"])


# ---------------------------------------------------------------------------
# CLI / schema / tooling wiring
# ---------------------------------------------------------------------------
def test_race_cli_json_section_schema5():
    proc = _run_cli("--race", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema_version"] == 6
    race = payload["race"]
    assert race["n_files"] >= 40
    assert race["hierarchy"] == sorted(race["hierarchy"])
    assert len(race["hierarchy"]) == len(race["edges"])
    # the race section appears only with --race
    proc = _run_cli("--cost", "--json", "--model", "mlp_infer")
    assert "race" not in json.loads(proc.stdout)


def test_parse_log_reads_race_section_and_refuses_newer(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    doc = {"version": 1, "schema_version": 5, "findings": [],
           "race": {"n_files": 3,
                    "locks": ["A._lock", "B._lock"],
                    "guards": {"A._heap": ["A._lock"]},
                    "edges": [{"outer": "A._lock", "inner": "B._lock",
                               "site": "a.py:7"}],
                    "hierarchy": [["A._lock", "B._lock"]]}}
    rows = dict(parse_log.parse_analysis_json(doc))
    assert rows["race.n_files"] == 3
    assert rows["race.n_locks"] == 2
    assert rows["race.n_guarded_attrs"] == 1
    assert rows["race.n_edges"] == 1
    assert rows["race.n_pinned"] == 1
    assert rows['race.guard{attr="A._heap"}'] == "A._lock"
    assert rows['race.edge{outer="A._lock",inner="B._lock"}'] == "a.py:7"
    with pytest.raises(ValueError, match="newer"):
        parse_log.parse_analysis_json(dict(doc, schema_version=7))
    # end to end: a schema-7 document is refused through the CLI
    newer = tmp_path / "newer.json"
    newer.write_text(json.dumps(dict(doc, schema_version=7)))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         str(newer)], capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0
    assert "newer" in (proc.stderr + proc.stdout)


def test_self_check_runs_race_sweep():
    """Mutating one threaded module with a guard violation must fail
    self_check through lint_threaded_sources — proves the sweep is
    armed (without actually breaking the shipped tree: we assert the
    wiring by flag instead)."""
    from mxnet_tpu.analysis import self_check
    clean = self_check(with_coverage=False, with_cost=False,
                       with_examples=False, with_workers=False,
                       with_serving=False, with_telemetry=False,
                       with_shard=False, with_mlops=False, with_race=True)
    assert [f for f in clean if f.rule_id.startswith("RACE")] == []
    # and the race pass is genuinely what ran: disabling it is the only
    # difference between these two calls
    no_race = self_check(with_coverage=False, with_cost=False,
                         with_examples=False, with_workers=False,
                         with_serving=False, with_telemetry=False,
                         with_shard=False, with_mlops=False,
                         with_race=False)
    assert no_race == []


def test_hierarchy_drift_fails_the_sweep(tmp_path):
    """Pin a stale row / omit a real edge: lint_threaded_sources must
    flag both directions against the alternate table."""
    doc = tmp_path / "concurrency.md"
    real = rl.parse_hierarchy(HIERARCHY)
    kept = real[1:]   # drop one observed edge from the pinned table
    rows = ["| # | outer | inner | why |", "|---|---|---|---|"]
    rows += ["| %d | `%s` | `%s` | kept |" % (i, o, inn)
             for i, (o, inn) in enumerate(kept, 1)]
    rows.append("| 99 | `Ghost._a` | `Ghost._b` | stale |")
    doc.write_text("\n".join(rows) + "\n")
    findings = rl.lint_threaded_sources(hierarchy=str(doc))
    msgs = [f.message for f in findings if f.rule_id == "RACE002"]
    assert any("not pinned" in m for m in msgs)
    assert any("Ghost._a -> Ghost._b" in m for m in msgs)
