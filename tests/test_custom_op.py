"""Python CustomOp bridge tests (reference:
tests/python/unittest/test_operator.py test_custom_op)."""
import numpy as np
import pytest

import mxnet_tpu as mx


@mx.operator.register("_test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        return _Sigmoid()


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        y = 1.0 / (1.0 + mx.nd.exp(-in_data[0]))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


@mx.operator.register("_test_addn")
class _AddNProp(mx.operator.CustomOpProp):
    def __init__(self, num_args="2"):
        super().__init__(need_top_grad=True)
        self._num = int(num_args)

    def list_arguments(self):
        return ["arg%d" % i for i in range(self._num)]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _AddN()


class _AddN(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        acc = in_data[0]
        for a in in_data[1:]:
            acc = acc + a
        self.assign(out_data[0], req[0], acc)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for g in in_grad:
            self.assign(g, "write", out_grad[0])


def test_custom_sigmoid_forward_backward():
    x = mx.nd.array(np.array([0.0, 1.0, -2.0], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        out = mx.nd.Custom(x, op_type="_test_sigmoid")
        loss = mx.nd.sum(out)
    loss.backward()
    ref = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(), ref * (1 - ref), rtol=1e-5)


def test_custom_multi_input_with_params():
    a = mx.nd.array(np.ones(4, np.float32))
    b = mx.nd.array(np.full(4, 2.0, np.float32))
    c = mx.nd.array(np.full(4, 3.0, np.float32))
    a.attach_grad()
    with mx.autograd.record():
        out = mx.nd.Custom(a, b, c, op_type="_test_addn", num_args=3)
        mx.nd.sum(out).backward()
    np.testing.assert_allclose(out.asnumpy(), 6.0)
    np.testing.assert_allclose(a.grad.asnumpy(), 1.0)


def test_custom_composes_with_builtin_ops():
    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        h = x * 3.0
        s = mx.nd.Custom(h, op_type="_test_sigmoid")
        loss = mx.nd.sum(s * s)
    loss.backward()
    xn = x.asnumpy()
    sig = 1 / (1 + np.exp(-3 * xn))
    expect = 2 * sig * sig * (1 - sig) * 3
    np.testing.assert_allclose(x.grad.asnumpy(), expect, rtol=1e-5)


def test_custom_unknown_type_raises():
    with pytest.raises(mx.MXNetError):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="_no_such_op")
