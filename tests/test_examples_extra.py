"""Round-2 example scripts run end-to-end and learn (reference: the
example/ tree is executable documentation — recommenders, rnn/bucketing)."""
import importlib.util
import os
import sys

import pytest

# full example trainings are the nightly tier; the tier-1 `-m "not slow"`
# run must finish <10 min on a 1-core host (VERDICT r5 weak 3)
pytestmark = pytest.mark.slow

_EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(path, argv):
    spec = importlib.util.spec_from_file_location("ex_mod_%s" %
                                                  os.path.basename(path),
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    saved = sys.argv
    sys.argv = ["x"] + argv
    try:
        mod.main()   # each example asserts its own learning criterion
    finally:
        sys.argv = saved


def test_matrix_factorization_example():
    _run(os.path.join(_EXAMPLES, "recommenders", "matrix_fact.py"),
         ["--epochs", "8"])


def test_char_lm_bucketing_example():
    _run(os.path.join(_EXAMPLES, "rnn_lm", "char_lm.py"),
         ["--epochs", "4"])


def test_wide_deep_example():
    _run(os.path.join(_EXAMPLES, "wide_deep", "train.py"),
         ["--num-batches", "100"])


def test_dcgan_example():
    """Adversarial training end-to-end: Conv2DTranspose generator vs conv
    discriminator, alternating updates (reference: example/gan/dcgan.py)."""
    _run(os.path.join(_EXAMPLES, "gan", "dcgan.py"), ["--steps", "150"])


# -- round 3 (VERDICT r2 #7): detector + autoencoder + multi-task + nce ----
def test_rcnn_lite_example():
    """Faster-RCNN-lite: Proposal + ROIAlign + bipartite_matching get an
    end-to-end consumer that learns (reference: example/rcnn/)."""
    _run(os.path.join(_EXAMPLES, "rcnn", "train_rcnn_lite.py"),
         ["--steps", "100"])


def test_autoencoder_example():
    """Stacked AE + KL-sparseness penalty (reference:
    example/autoencoder/)."""
    _run(os.path.join(_EXAMPLES, "autoencoder", "train_ae.py"),
         ["--epochs", "15"])


def test_multi_task_example():
    """Two SoftmaxOutput heads on one trunk (reference:
    example/multi-task/)."""
    _run(os.path.join(_EXAMPLES, "multi_task", "train_multi_task.py"),
         ["--epochs", "10"])


def test_nce_loss_example():
    """NCE word embeddings (reference: example/nce-loss/)."""
    _run(os.path.join(_EXAMPLES, "nce_loss", "train_nce.py"),
         ["--steps", "600"])


def test_fgsm_adversary_example():
    """Input-gradient FGSM attack (reference: example/adversary/)."""
    _run(os.path.join(_EXAMPLES, "adversary", "fgsm.py"),
         ["--epochs", "6"])


def test_custom_softmax_example():
    """Training through a numpy CustomOp (reference:
    example/numpy-ops/custom_softmax.py)."""
    _run(os.path.join(_EXAMPLES, "numpy_ops", "custom_softmax.py"),
         ["--epochs", "10"])

# -- round 4 (VERDICT r3 #4): segmentation + VAE + RL + style + text-cnn --
def test_fcn_segmentation_example():
    """FCN-8s: Deconvolution upsampling + Crop alignment + Bilinear/Mixed
    init + per-pixel SoftmaxOutput (reference: example/fcn-xs/)."""
    _run(os.path.join(_EXAMPLES, "fcn_xs", "train_fcn.py"),
         ["--epochs", "8"])


def test_vae_example():
    """Reparameterized stochastic latent + analytic KL inside autograd
    (reference: example/vae/VAE.py)."""
    _run(os.path.join(_EXAMPLES, "vae", "train_vae.py"),
         ["--epochs", "30"])


def test_dqn_example():
    """Replay buffer + frozen target net + epsilon-greedy; asserts the
    greedy policy is optimal (reference:
    example/reinforcement-learning/dqn/)."""
    _run(os.path.join(_EXAMPLES, "reinforcement_learning", "dqn.py"),
         ["--episodes", "80"])


def test_neural_style_example():
    """Optimize-the-input: gradients w.r.t. data through a frozen
    extractor, optimizer driving a raw NDArray (reference:
    example/neural-style/nstyle.py)."""
    _run(os.path.join(_EXAMPLES, "neural_style", "nstyle.py"),
         ["--steps", "150"])


def test_text_cnn_example():
    """Kim-style multi-width conv + max-over-time text classifier
    (reference: example/cnn_text_classification/text_cnn.py)."""
    _run(os.path.join(_EXAMPLES, "cnn_text_classification",
                      "text_cnn.py"), ["--epochs", "12"])


# -- round 4: bi-lstm-sort + capsnet + stochastic-depth + NER -------------
def test_bi_lstm_sort_example():
    """BiLSTM learns to emit its input sorted — every output position
    needs global context (reference: example/bi-lstm-sort/)."""
    _run(os.path.join(_EXAMPLES, "bi_lstm_sort", "sort_lstm.py"),
         ["--epochs", "25"])


def test_capsnet_example():
    """Dynamic routing-by-agreement + margin loss (reference:
    example/capsnet/capsulenet.py)."""
    _run(os.path.join(_EXAMPLES, "capsnet", "capsnet.py"),
         ["--epochs", "10"])


def test_stochastic_depth_example():
    """Bernoulli-gated residual branches, deterministic inference
    (reference: example/stochastic-depth/sd_module.py)."""
    _run(os.path.join(_EXAMPLES, "stochastic_depth", "sd_resnet.py"),
         ["--epochs", "8"])


def test_ner_example():
    """BiLSTM BIO tagger with span-level scoring (reference:
    example/named_entity_recognition/)."""
    _run(os.path.join(_EXAMPLES, "named_entity_recognition",
                      "ner_lstm.py"), ["--epochs", "15"])
