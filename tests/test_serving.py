"""mxnet_tpu.serving: bucketed recompile-free inference (tier-1).

The four contract points of the serving layer (ISSUE 2 acceptance):
(a) batched-padded results are numerically identical to unbatched
forward for every bucket, (b) a 200-request concurrent load after warmup
triggers ZERO new jit compilations (asserted through the exposed
jit-cache key counter), (c) queue overflow rejects rather than stalls,
(d) graceful drain completes in-flight requests.  Plus the HTTP front
end, the SRV serving lint, the CLI builders, and the examples/serving
demo.
"""
import http.client
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (Batcher, Draining, ModelRunner, Server,
                               ServerBusy)

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

BUCKETS = (1, 4, 8)
FEAT = 8
NCLS = 3


def _mlp_symbol():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    return mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=NCLS, name="fc2"),
        name="softmax")


def _bound_module():
    mod = mx.mod.Module(_mlp_symbol())
    max_b = max(BUCKETS)
    mod.bind(data_shapes=[("data", (max_b, FEAT))],
             label_shapes=[("softmax_label", (max_b,))],
             for_training=False)
    mod.init_params(mx.init.Xavier())
    return mod


def _hybrid_block():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(NCLS))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return net


def _numpy_mlp_oracle(mod, x):
    """Independent forward: softmax(relu(x W1^T + b1) W2^T + b2)."""
    arg, _ = mod.get_params()
    h = x @ arg["fc1_weight"].asnumpy().T + arg["fc1_bias"].asnumpy()
    h = np.maximum(h, 0.0)
    z = h @ arg["fc2_weight"].asnumpy().T + arg["fc2_bias"].asnumpy()
    e = np.exp(z - z.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


# ---------------------------------------------------------------- (a)
def test_bucket_padding_equivalence_module():
    """Padded-bucket execution returns, for every request size spanning
    every bucket (and the above-max chunking path), exactly what an
    unpadded forward computes."""
    mod = _bound_module()
    runner = ModelRunner(mod, buckets=BUCKETS)
    rng = np.random.RandomState(3)
    X = rng.randn(20, FEAT).astype(np.float32)
    ref = _numpy_mlp_oracle(mod, X)
    for n in (1, 2, 3, 4, 5, 7, 8, 9, 20):  # covers 1/4/8 + chunking
        out = runner.forward_batch(X[:n])
        assert out.shape == (n, NCLS)
        np.testing.assert_allclose(out, ref[:n], rtol=1e-5, atol=1e-6)
    # single-example surface
    np.testing.assert_allclose(runner.predict(X[0]), ref[0],
                               rtol=1e-5, atol=1e-6)


def test_bucket_padding_equivalence_gluon():
    """Row i's result must not depend on how the batch was padded: every
    batch size gives the same per-row answer as the bucket-1 path."""
    net = _hybrid_block()
    runner = ModelRunner(net, buckets=BUCKETS, example_shape=(FEAT,))
    rng = np.random.RandomState(4)
    X = rng.randn(8, FEAT).astype(np.float32)
    singles = np.stack([runner.predict(X[i]) for i in range(len(X))])
    for n in (2, 3, 4, 6, 8):
        np.testing.assert_allclose(runner.forward_batch(X[:n]), singles[:n],
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------- (b)
@pytest.mark.parametrize("kind", ["module", "gluon"])
def test_zero_recompiles_under_200_request_concurrent_load(kind):
    """After AOT warmup, 200 concurrent requests across every bucket add
    ZERO jit-cache keys — the recompile-free steady state, asserted via
    the cache-key counter exposed by Module/HybridBlock."""
    if kind == "module":
        runner = ModelRunner(_bound_module(), buckets=BUCKETS)
    else:
        runner = ModelRunner(_hybrid_block(), buckets=BUCKETS,
                             example_shape=(FEAT,))
    assert runner.warmed_up
    warm_keys = runner.jit_cache_keys()
    assert len(warm_keys) >= len(BUCKETS)

    batcher = Batcher(runner, batch_timeout_ms=1.0, max_queue=512)
    rng = np.random.RandomState(5)
    X = rng.randn(32, FEAT).astype(np.float32)
    direct = np.stack([runner.predict(X[i]) for i in range(len(X))])

    errors = []

    def client(tid, n=25):
        try:
            for i in range(n):
                row = (tid * n + i) % len(X)
                out = batcher.infer(X[row], timeout=60)
                np.testing.assert_allclose(out, direct[row],
                                           rtol=1e-5, atol=1e-6)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=client, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    batcher.drain()
    assert not errors, errors[0]
    assert batcher.stats.requests_total == 200
    # the serving contract: the jit-cache key set did not grow
    assert runner.jit_cache_keys() == warm_keys, (
        "steady-state recompile: %r" % (runner.jit_cache_keys() - warm_keys))
    assert runner.recompiles_since_warmup() == 0


# ---------------------------------------------------------------- (c)
def test_queue_overflow_rejects_not_stalls():
    runner = ModelRunner(_hybrid_block(), buckets=(1,), example_shape=(FEAT,))
    slow = threading.Event()
    real = runner.forward_batch
    runner.forward_batch = lambda x: (slow.wait(10), real(x))[1]
    batcher = Batcher(runner, batch_timeout_ms=0.0, max_queue=2)
    x = np.zeros(FEAT, np.float32)
    t0 = time.monotonic()
    admitted, rejected = [], 0
    # worker takes 1 request and blocks in the model; queue holds 2 more;
    # everything beyond that must reject IMMEDIATELY, not stall
    for _ in range(10):
        try:
            admitted.append(batcher.submit(x))
        except ServerBusy:
            rejected += 1
    elapsed = time.monotonic() - t0
    assert rejected >= 7, (len(admitted), rejected)
    assert elapsed < 5.0, "submit stalled %.1fs instead of rejecting" % elapsed
    assert batcher.stats.rejected_total == rejected
    slow.set()
    batcher.drain()
    for p in admitted:  # admitted requests still complete
        assert p.result(10) is not None


# ---------------------------------------------------------------- (d)
def test_graceful_drain_completes_inflight():
    runner = ModelRunner(_hybrid_block(), buckets=BUCKETS,
                         example_shape=(FEAT,))
    real = runner.forward_batch
    runner.forward_batch = lambda x: (time.sleep(0.05), real(x))[1]
    batcher = Batcher(runner, batch_timeout_ms=1.0, max_queue=64)
    X = np.random.RandomState(6).randn(10, FEAT).astype(np.float32)
    pending = [batcher.submit(X[i]) for i in range(10)]
    assert batcher.drain(timeout=30)
    for i, p in enumerate(pending):
        assert p.done()
        np.testing.assert_allclose(p.result(0.1), runner.predict(X[i]),
                                   rtol=1e-5, atol=1e-6)
    with pytest.raises(Draining):
        batcher.submit(X[0])
    # idempotent
    assert batcher.drain()


# ------------------------------------------------------------- HTTP
def _post(conn, path, payload):
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp, json.loads(resp.read())


def test_http_server_endpoints_and_drain():
    runner = ModelRunner(_hybrid_block(), buckets=BUCKETS,
                         example_shape=(FEAT,))
    server = Server(runner, port=0, batch_timeout_ms=1.0, max_queue=64)
    host, port = server.start()
    conn = http.client.HTTPConnection(host, port, timeout=30)
    rng = np.random.RandomState(7)
    x1 = rng.randn(FEAT).astype(np.float32)
    X = rng.randn(3, FEAT).astype(np.float32)

    resp, body = _post(conn, "/predict", {"data": x1.tolist()})
    assert resp.status == 200
    np.testing.assert_allclose(body["outputs"], runner.predict(x1),
                               rtol=1e-5, atol=1e-6)
    resp, body = _post(conn, "/predict", {"data": X.tolist()})
    assert resp.status == 200
    np.testing.assert_allclose(body["outputs"], runner.forward_batch(X),
                               rtol=1e-5, atol=1e-6)

    resp, body = _post(conn, "/predict", {"data": [[0.0] * (FEAT + 1)]})
    assert resp.status == 400
    resp, body = _post(conn, "/predict", {"wrong": 1})
    assert resp.status == 400

    conn.request("GET", "/healthz")
    resp = conn.getresponse()
    assert resp.status == 200
    assert json.loads(resp.read())["status"] == "ok"

    conn.request("GET", "/stats")
    stats = json.loads(conn.getresponse().read())
    assert stats["requests_total"] >= 4
    assert stats["recompiles"] == 0
    assert stats["buckets_configured"] == list(BUCKETS)
    for b in stats["buckets"].values():
        assert {"count", "p50_ms", "p99_ms"} <= set(b)
    assert 0.0 <= stats["batch_fill_ratio"] <= 1.0
    conn.close()

    server.drain()
    with pytest.raises(Draining):
        server.batcher.submit(x1)


def test_http_backpressure_429():
    runner = ModelRunner(_hybrid_block(), buckets=(1,), example_shape=(FEAT,))
    slow = threading.Event()
    real = runner.forward_batch
    runner.forward_batch = lambda x: (slow.wait(15), real(x))[1]
    server = Server(runner, port=0, batch_timeout_ms=0.0, max_queue=1)
    host, port = server.start()
    x = [0.0] * FEAT
    statuses, lock = [], threading.Lock()

    def client():
        conn = http.client.HTTPConnection(host, port, timeout=30)
        resp, _ = _post(conn, "/predict", {"data": x})
        if resp.status == 429:
            assert resp.getheader("Retry-After") is not None
        with lock:
            statuses.append(resp.status)
        conn.close()

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.5)   # let them all hit the 1-deep queue
    slow.set()
    for t in threads:
        t.join()
    assert statuses.count(429) >= 1, statuses
    assert statuses.count(200) >= 1, statuses
    server.drain()


# ------------------------------------------------------ serving lint
def test_serving_lint_clean_mlp():
    from mxnet_tpu.analysis import lint_serving
    assert lint_serving(_mlp_symbol(),
                        data_shapes={"data": (8, FEAT)}) == []


def test_serving_lint_flags_baked_batch():
    from mxnet_tpu.analysis import lint_serving
    data = mx.sym.Variable("data")
    flat = mx.sym.Reshape(data, shape=(8, FEAT), name="rs")  # baked batch
    sym = mx.sym.FullyConnected(flat, num_hidden=4, name="fc")
    findings = lint_serving(sym, data_shapes={"data": (8, FEAT)})
    rules = {f.rule_id for f in findings}
    assert "SRV002" in rules, findings
    assert "SRV001" in rules, findings  # batch x2 breaks/bakes shapes


def test_model_runner_refuses_non_polymorphic_symbol():
    data = mx.sym.Variable("data")
    flat = mx.sym.Reshape(data, shape=(8, FEAT), name="rs")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(flat, num_hidden=NCLS, name="fc"),
        name="softmax")
    mod = mx.mod.Module(sym)
    mod.bind(data_shapes=[("data", (8, FEAT))],
             label_shapes=[("softmax_label", (8,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    with pytest.raises(MXNetError, match="recompile-free"):
        ModelRunner(mod, buckets=BUCKETS)
    # lint=False opts out (single-bucket serving of a baked graph is legal)
    runner = ModelRunner(mod, buckets=(8,), lint=False)
    assert runner.forward_batch(
        np.zeros((3, FEAT), np.float32)).shape == (3, NCLS)


# ------------------------------------------------ CLI + example + CI
def _load_tool(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_serve_cli_demo_runner():
    serve = _load_tool("serve_tool", os.path.join(_ROOT, "tools", "serve.py"))
    args = serve.parse_args(["--demo", "--buckets", "1,4",
                             "--data-shape", "8"])
    runner = serve.build_demo_runner(args)
    assert runner.buckets == (1, 4)
    assert runner.warmed_up
    assert runner.forward_batch(
        np.zeros((3, 8), np.float32)).shape == (3, 10)


def test_serve_cli_module_checkpoint(tmp_path):
    mod = _bound_module()
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 1)
    serve = _load_tool("serve_tool2", os.path.join(_ROOT, "tools",
                                                  "serve.py"))
    args = serve.parse_args(["--prefix", prefix, "--epoch", "1",
                             "--data-shape", str(FEAT),
                             "--buckets", "1,4,8"])
    runner = serve.build_module_runner(args)
    x = np.random.RandomState(8).randn(5, FEAT).astype(np.float32)
    np.testing.assert_allclose(runner.forward_batch(x),
                               _numpy_mlp_oracle(mod, x),
                               rtol=1e-5, atol=1e-6)


def test_serving_example():
    """examples/serving/serve_demo.py end-to-end (train -> checkpoint ->
    serve -> concurrent load -> drain), its own asserts armed."""
    path = os.path.join(_ROOT, "examples", "serving", "serve_demo.py")
    spec = importlib.util.spec_from_file_location("serving_demo", path)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    saved = sys.argv
    sys.argv = ["x", "--epochs", "8", "--clients", "4", "--per-client", "5"]
    try:
        m.main()
    finally:
        sys.argv = saved


def test_analysis_cli_over_serving_sources():
    """CI gate: the mxlint source pass runs clean (no trace-time traps)
    over the serving example and the serve CLI."""
    for target in (os.path.join(_ROOT, "examples", "serving",
                                "serve_demo.py"),
                   os.path.join(_ROOT, "tools", "serve.py")):
        proc = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.analysis", target],
            capture_output=True, text=True, cwd=_ROOT,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=300)
        assert proc.returncode == 0, (target, proc.stdout, proc.stderr)


def test_serving_bench_keys():
    """bench.py's serving stage contract: live reqs/sec + p50/p99 keys,
    measured on the host without any TPU."""
    from mxnet_tpu.serving.bench import serving_bench
    out = serving_bench(n_requests=80, concurrency=4, buckets=(1, 4, 8),
                        feat=FEAT)
    assert out["serving_reqs_per_sec"] > 0
    assert 0 < out["serving_p50_ms"] <= out["serving_p99_ms"]
    assert out["serving_recompiles"] == 0
    assert out["serving_requests"] == 80
