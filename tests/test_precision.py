"""Mixed precision end-to-end (ISSUE 18): bf16 training with f32
masters inside the ZeRO-1 shard, dynamic loss scaling spelled into the
fused optimizer kernels, int8 KV-cache serving, and the real PTQ
pipeline judged by the promotion controller.

Contract points:
(a) the loss-scale machine: grow after GROWTH_INTERVAL consecutive
    finite steps (capped), halve on inf/nan (floored), skipped steps
    are true no-ops with the skipped counter advancing;
(b) fused-vs-unfused loss-scaled update equivalence at the PR-15
    tolerance, including the bitwise select-skip;
(c) bf16 + ZeRO-1 tracks the f32 replicated loss trajectory over >= 20
    steps while the f32 masters stay PHYSICALLY sharded
    (addressable_shards-asserted) and the live params are bf16;
(d) the precision mutation seams fail the unmodified STATIC_BUDGETS
    gate rc=2 through the real CLI: PRECISION_MASTER_F32 busts the
    pinned bf16/f32 peak-HBM ratio (COST001), PRECISION_F32_GRAD_REDUCE
    reduces bf16 on the wire (tightened DST004);
(e) mixed-precision checkpoints resize: save at k=2, restore at k=4,
    masters bitwise, params exactly cast(master);
(f) int8 KV-cache greedy decode agrees with the f32-cache reference at
    the runner level, with the page bytes actually shrinking;
(g) the PTQ pipeline: per-channel quantization from a real calibration
    set holds golden parity, and a deliberately-broken quant (scrambled
    scales) is auto-rolled-back by the promotion controller with the
    audit record naming golden_parity;
(h) tools/capacity.py --tokens --kv-dtype int8 needs fewer replicas
    than f32 on the pinned scenario.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import precision as prec
from mxnet_tpu.parallel import DataParallelTrainer, make_mesh
from mxnet_tpu.resilience import checkpoint as ckpt

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

FEAT = 8
NCLS = 3


def _cpu_env(devices=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if devices:
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=%d" % devices)
    else:
        env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _trainer(k, zero=1, dtype="bf16", seed=3, hidden=(32,), classes=10,
             optimizer="sgd"):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    for h in hidden:
        net.add(gluon.nn.Dense(h, activation="relu"))
    net.add(gluon.nn.Dense(classes))
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((k,), ("data",), jax.devices()[:k]) if k else None
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer,
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh, zero=zero,
        dtype=dtype)


def _batches(n, batch=24, seed=0, feat=16, classes=10):
    rng = np.random.RandomState(seed)
    return [(mx.nd.array(rng.rand(batch, feat).astype(np.float32)),
             mx.nd.array(rng.randint(0, classes, batch).astype(np.int64)))
            for _ in range(n)]


# ---------------------------------------------------------------------------
# (a) the loss-scale state machine
# ---------------------------------------------------------------------------
def test_loss_scale_grow_after_interval():
    scale, good = prec.init_loss_scale()
    assert float(scale) == prec.LOSS_SCALE_INIT
    for i in range(prec.GROWTH_INTERVAL):
        scale, good = prec.loss_scale_update(scale, good, True)
    assert float(scale) == prec.LOSS_SCALE_INIT * prec.GROWTH_FACTOR
    assert int(good) == 0     # counter resets on growth
    # growth caps at MAX_SCALE
    scale = jnp.float32(prec.MAX_SCALE)
    good = jnp.int32(prec.GROWTH_INTERVAL - 1)
    scale, good = prec.loss_scale_update(scale, good, True)
    assert float(scale) == prec.MAX_SCALE


def test_loss_scale_backoff_and_floor():
    scale, good = prec.init_loss_scale()
    # a run of good steps, then one inf: halve + reset the counter
    for _ in range(5):
        scale, good = prec.loss_scale_update(scale, good, True)
    assert int(good) == 5
    scale, good = prec.loss_scale_update(scale, good, False)
    assert float(scale) == prec.LOSS_SCALE_INIT * prec.BACKOFF_FACTOR
    assert int(good) == 0
    # backoff floors at MIN_SCALE
    scale = jnp.float32(prec.MIN_SCALE)
    scale, good = prec.loss_scale_update(scale, jnp.int32(0), False)
    assert float(scale) == prec.MIN_SCALE


def test_all_finite_probe():
    ok = prec.all_finite([jnp.ones(4), jnp.zeros(3)])
    assert bool(ok)
    bad = prec.all_finite([jnp.ones(4),
                           jnp.array([1.0, np.inf])])
    assert not bool(bad)
    assert bool(prec.all_finite([]))


def test_trainer_inf_batch_skips_step_and_books_it():
    """An inf in the batch poisons the grads: the step is a select-skip
    (params bitwise-untouched), the scale halves, the skipped counter
    advances, and training continues on the next finite batch."""
    tr = _trainer(2, zero=1, dtype="bf16")
    x, y = _batches(1, seed=5)[0]
    tr.step(x, y)
    before = [np.asarray(p.data()._data).copy()
              for p in tr._params_by_name.values()]
    master_before = np.asarray(tr._zero_master).copy()
    scale_before = float(tr._ls_scale)

    xb = np.asarray(x.asnumpy(), np.float32).copy()
    xb[0, 0] = np.inf
    tr.step(mx.nd.array(xb), y)
    after = [np.asarray(p.data()._data)
             for p in tr._params_by_name.values()]
    for a, b in zip(before, after):
        assert a.tobytes() == b.tobytes()
    assert np.asarray(tr._zero_master).tobytes() \
        == master_before.tobytes()
    assert float(tr._ls_scale) == scale_before * prec.BACKOFF_FACTOR
    assert int(tr._ls_skipped) == 1
    assert int(tr._ls_good) == 0

    # and the machine keeps training afterwards
    tr.step(x, y)
    assert int(tr._ls_skipped) == 1
    assert int(tr._ls_good) == 1


def test_flush_publishes_loss_scale_telemetry():
    from mxnet_tpu.telemetry.metrics import registry
    tr = _trainer(2, zero=1, dtype="bf16")
    x, y = _batches(1, seed=6)[0]
    tr.step(x, y)
    tr.flush()
    text = registry().prometheus_text()
    assert "mxtpu_loss_scale" in text


# ---------------------------------------------------------------------------
# (b) fused vs unfused loss-scaled update
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("opt_name", ["sgd_momentum", "adam"])
def test_fused_loss_scaled_update_matches_unfused(opt_name):
    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.ops import fused_optimizer as fo
    from mxnet_tpu.parallel.functional import functional_optimizer_update

    rng = np.random.RandomState(3)
    n = 4096
    w = jnp.asarray(rng.randn(n).astype("f"))
    g = jnp.asarray(rng.randn(n).astype("f"))
    scale = 1024.0
    if opt_name == "adam":
        opt = opt_mod.Adam(learning_rate=0.01, wd=1e-4)
        state = (jnp.asarray(rng.randn(n).astype("f")),
                 jnp.asarray(np.abs(rng.randn(n)).astype("f")))
    else:
        opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4)
        state = jnp.asarray(rng.randn(n).astype("f"))
    lr, t = jnp.float32(0.05), jnp.int32(3)
    inv = jnp.float32(1.0 / scale)

    fw, fs = fo.fused_optimizer_update(opt, 0, w, g, state, lr, t,
                                       inv_scale=inv, ok=jnp.float32(1.0),
                                       interpret=True)
    uw, us = functional_optimizer_update(opt, 0, w, g * inv, state, lr, t)
    assert float(jnp.max(jnp.abs(fw - uw))) <= 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(fs),
                    jax.tree_util.tree_leaves(us)):
        assert float(jnp.max(jnp.abs(a - b))) <= 1e-5


def test_fused_update_skip_is_bitwise_noop():
    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.ops import fused_optimizer as fo

    rng = np.random.RandomState(4)
    w = jnp.asarray(rng.randn(512).astype("f"))
    g = jnp.asarray(rng.randn(512).astype("f")).at[7].set(np.nan)
    m = jnp.asarray(rng.randn(512).astype("f"))
    opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9)
    nw, nm = fo.fused_optimizer_update(
        opt, 0, w, g, m, jnp.float32(0.1), jnp.int32(1),
        inv_scale=jnp.float32(1.0), ok=jnp.float32(0.0), interpret=True)
    assert np.asarray(nw).tobytes() == np.asarray(w).tobytes()
    assert np.asarray(nm).tobytes() == np.asarray(m).tobytes()


# ---------------------------------------------------------------------------
# (c) bf16 + ZeRO-1 convergence with physically sharded f32 masters
# ---------------------------------------------------------------------------
def test_bf16_zero1_tracks_f32_replicated_trajectory():
    """>= 20 steps, same seed/batches: the bf16 ZeRO-1 loss trajectory
    stays within the documented tolerance of the f32 replicated one
    (docs/precision.md), and both actually learn."""
    data = _batches(20, seed=7)
    tf32 = _trainer(4, zero=0, dtype="float32")
    l32 = [float(tf32.step(x, y)) for x, y in data]
    t16 = _trainer(4, zero=1, dtype="bf16")
    l16 = [float(t16.step(x, y)) for x, y in data]
    delta = max(abs(a - b) for a, b in zip(l32, l16))
    assert delta <= 0.05, (delta, l32[-1], l16[-1])
    assert l16[-1] < l16[0]
    assert int(t16._ls_skipped) == 0


def test_bf16_zero1_masters_physically_sharded():
    """The f32 masters exist ONLY as the ZeRO-1 shard: k addressable
    shards of (shard,) each, dtype f32 — while the live params the
    forward consumes are bf16."""
    t16 = _trainer(4, zero=1, dtype="bf16")
    x, y = _batches(1, seed=8)[0]
    t16.step(x, y)
    master = t16._zero_master
    assert master.dtype == jnp.dtype("float32")
    plan = t16._zero_plan
    shards = list(master.addressable_shards)
    assert len(shards) == 4
    assert {s.data.shape for s in shards} == {(plan.shard,)}
    assert master.shape == (plan.padded,)
    for p in t16._params_by_name.values():
        assert p.data()._data.dtype == jnp.dtype("bfloat16")
    # param == cast(master): the invariant the checkpoint path keeps
    full = np.asarray(master)[:plan.total]
    flat = np.concatenate(
        [np.asarray(p.data()._data, np.float32).ravel()
         for p in t16._params_by_name.values()])
    np.testing.assert_array_equal(
        flat, np.asarray(jnp.asarray(full).astype(jnp.bfloat16),
                         np.float32))


# ---------------------------------------------------------------------------
# (d) the mutation seams fail the unmodified gate rc=2 (real CLI)
# ---------------------------------------------------------------------------
def _seam_gate(tmp_path, mutation):
    script = tmp_path / "mutate.py"
    script.write_text(
        "import os, sys\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu import precision\n"
        "%s\n"
        "from mxnet_tpu.analysis.__main__ import main\n"
        "sys.exit(main(['--cost', '--budget', %r,\n"
        "               '--model', 'bf16_zero1_train_step']))\n"
        % (mutation, os.path.join(_ROOT, "STATIC_BUDGETS.json")))
    return subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=_ROOT,
                          env=_cpu_env(), timeout=600)


def test_master_f32_seam_fails_gate_rc2(tmp_path):
    """PRECISION_MASTER_F32=False re-derives the masters from a full
    per-rank flat f32 vector: the pinned bf16/f32 peak-HBM ratio busts
    (COST001 naming the row) and the unmodified gate exits 2."""
    proc = _seam_gate(tmp_path, "precision.PRECISION_MASTER_F32 = False")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "bf16_zero1_train_step.peak_hbm_bytes" in proc.stdout
    assert "COST001" in proc.stdout


def test_f32_grad_reduce_seam_fails_gate_rc2_dst004(tmp_path):
    """PRECISION_F32_GRAD_REDUCE=False reduces bf16 over the data axis:
    the tightened DST004 (sub-f32 collective reduce = gate failure)
    fires through the real CLI."""
    proc = _seam_gate(tmp_path,
                      "precision.PRECISION_F32_GRAD_REDUCE = False")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST004" in proc.stdout


def test_bf16_budget_row_relations():
    """The clean builder: no findings, and the pinned ratios hold with
    real margin (the extras the bench stage republishes)."""
    from mxnet_tpu.analysis import budget_models as bm
    report, findings, shard = bm.build_model("bf16_zero1_train_step")
    assert not findings, [str(f) for f in findings]
    x = shard.extras
    assert x["bf16_peak_hbm_ratio"] <= bm.BF16_PEAK_HBM_RATIO_CEILING
    assert x["bf16_collective_ratio"] <= bm.BF16_COLLECTIVE_RATIO_CEILING
    assert x["bf16_modeled_hbm_drop_pct"] >= 30.0


# ---------------------------------------------------------------------------
# (e) mixed-precision resize-on-resume
# ---------------------------------------------------------------------------
def test_bf16_resize_parity_save2_restore4(tmp_path):
    """Save the bf16/f32-master pair at k=2; restore at k=4: masters
    bitwise through the reassemble/re-pad path, params exactly
    cast(master), loss-scale state carried, and training continues
    deterministically."""
    d = str(tmp_path / "save2")
    t2 = _trainer(2, zero=1, dtype="bf16")
    data = _batches(4, seed=9)
    for x, y in data[:3]:
        t2.step(x, y)
    t2.flush()
    plan2 = t2._zero_plan
    ref_master = np.asarray(t2._zero_master)[:plan2.total].copy()
    ref_params = b"".join(np.asarray(p.data()._data).tobytes()
                          for p in t2._params_by_name.values())
    ref_scale = float(t2._ls_scale)
    t2.save_checkpoint(d, epoch=0, nbatch=2)

    t4 = _trainer(4, zero=1, dtype="bf16", seed=77)  # wrong seed: the
    cursor = t4.restore_checkpoint(d)                # restore must win
    assert cursor["step"] == 3
    plan4 = t4._zero_plan
    assert np.asarray(t4._zero_master)[:plan4.total].tobytes() \
        == ref_master.tobytes()
    got_params = b"".join(np.asarray(p.data()._data).tobytes()
                          for p in t4._params_by_name.values())
    assert got_params == ref_params
    assert float(t4._ls_scale) == ref_scale
    # params re-derive as the exact bf16 cast of the restored masters
    flat = np.concatenate(
        [np.asarray(p.data()._data, np.float32).ravel()
         for p in t4._params_by_name.values()])
    np.testing.assert_array_equal(
        flat, np.asarray(jnp.asarray(ref_master).astype(jnp.bfloat16),
                         np.float32))
    # and further training still works at the new size
    t4.step(*data[3])


def test_bf16_checkpoint_refuses_f32_trainer(tmp_path):
    """A mixed-precision checkpoint (f32 masters) refuses to restore
    into an f32 trainer — not silently different numerics."""
    d = str(tmp_path)
    t2 = _trainer(2, zero=1, dtype="bf16")
    x, y = _batches(1, seed=10)[0]
    t2.step(x, y)
    t2.save_checkpoint(d, epoch=0, nbatch=0)
    t32 = _trainer(2, zero=1, dtype="float32")
    with pytest.raises(Exception, match="[Mm]ixed-precision|master"):
        t32.restore_checkpoint(d)


# ---------------------------------------------------------------------------
# (f) int8 KV-cache at the runner level
# ---------------------------------------------------------------------------
def _decode_runner(kv_dtype):
    from mxnet_tpu.parallel.mesh import MeshPlan
    from mxnet_tpu.serving.decode import DecodeRunner
    from mxnet_tpu.transformer import TransformerLMConfig
    from mxnet_tpu.transformer.decode import DecodeProgram

    cfg = TransformerLMConfig(vocab_size=64, d_model=32, n_heads=4,
                              n_layers=2, d_ff=64, seq_len=64)
    prog = DecodeProgram(cfg, plan=MeshPlan(data=1), page_size=8,
                         kv_dtype=kv_dtype)
    params = prog.program.init_params(0)
    return DecodeRunner(prog, params, slots=2, prefill_buckets=(8, 16),
                        warmup=False)


def test_int8_kv_decode_matches_f32_reference():
    """Greedy decode over the int8 KV cache agrees with the f32-cache
    runner token-for-token on the pinned prompts, and the page bytes
    actually shrink (codes + per-page scales < f32 rows)."""
    r8 = _decode_runner("int8")
    r32 = _decode_runner(None)
    assert r8.program.bytes_per_page() < r32.program.bytes_per_page()
    rng = np.random.RandomState(5)
    agree = total = 0
    for _ in range(4):
        p = rng.randint(1, 64, size=rng.randint(3, 12)).astype(np.int32)
        a = np.asarray(r8.generate(p, 6))
        b = np.asarray(r32.generate(p, 6))
        agree += int((a == b).sum())
        total += len(a)
    assert agree / total >= 0.9, (agree, total)


def test_int8_kv_admission_learns_halved_pages():
    """SRV004 admission prices the int8 pool at the quantized page
    bytes: the same geometry admits strictly cheaper."""
    r8 = _decode_runner("int8")
    r32 = _decode_runner(None)
    assert r8.admission_hbm_bytes() < r32.admission_hbm_bytes()


# ---------------------------------------------------------------------------
# (g) the PTQ pipeline + promotion-controller rollback
# ---------------------------------------------------------------------------
def _build_net(hidden=16):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(hidden, activation="relu"))
    net.add(gluon.nn.Dense(NCLS))
    return net


def _train_checkpoint(seed, steps, ckdir, run_id):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = _build_net()
    net.initialize(mx.init.Xavier())
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05}, run_id=run_id)
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        trainer.step(mx.nd.array(rng.rand(8, FEAT).astype(np.float32)),
                     mx.nd.array(rng.randint(0, NCLS, 8).astype(np.int64)))
    trainer.flush()
    return trainer.save_checkpoint(ckdir, epoch=0, nbatch=steps)


_CALIB_RNG = np.random.RandomState(21)
_CALIB = _CALIB_RNG.rand(64, FEAT).astype(np.float32)


def _scramble(model):
    """Deterministically trash the per-channel scales — the injected
    quantization regression the controller must roll back."""
    srng = np.random.RandomState(7)
    for layer in model.layers:
        signs = np.where(srng.rand(*layer.scales.shape) < 0.5,
                         -1.0, 1.0).astype(np.float32)
        layer.scales = (srng.permutation(layer.scales)
                        * srng.uniform(4.0, 9.0, layer.scales.shape)
                        .astype(np.float32) * signs)
    model._digest = None
    return model


def test_ptq_quantized_net_holds_parity():
    """The per-channel PTQ twin of a trained net: argmax parity vs the
    f32 net on fresh data, digest stable across requantization, digest
    moved by a scale scramble."""
    from mxnet_tpu.serving.quantize import (build_quantized_net,
                                            ptq_quantize_net)
    mx.random.seed(2)
    np.random.seed(2)
    net = _build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    _ = net(mx.nd.array(_CALIB[:4]))
    model = ptq_quantize_net(net, _CALIB)
    model2 = ptq_quantize_net(net, _CALIB)
    assert model.digest == model2.digest
    qnet = build_quantized_net(model)
    rng = np.random.RandomState(33)
    x = rng.rand(64, FEAT).astype(np.float32)
    ref = net(mx.nd.array(x)).asnumpy()
    out = qnet(mx.nd.array(x)).asnumpy()
    assert (out.argmax(1) == ref.argmax(1)).mean() >= 0.9
    scrambled = _scramble(ptq_quantize_net(net, _CALIB))
    assert scrambled.digest != model.digest


def test_ptq_scrambled_scales_rolled_back_naming_golden_parity(tmp_path):
    """THE serving acceptance test: a quantized fleet variant whose
    scales were deliberately scrambled drops golden parity below the
    threshold; the PR-12 promotion controller auto-rolls it back with
    the audit record naming golden_parity, and the incumbent keeps
    serving its original bytes."""
    from mxnet_tpu.mlops import PromotionController, read_audit_records
    from mxnet_tpu.serving import ModelFleet, ModelRunner, RequestShed
    from mxnet_tpu.serving.quantize import (build_quantized_net,
                                            quantized_runner_from_checkpoint)

    ck_inc = str(tmp_path / "inc")
    watch = str(tmp_path / "watch")
    audit = str(tmp_path / "audit")
    path = _train_checkpoint(0, 3, ck_inc, "ptq-inc")

    def factory(path_, rec):
        runner, prov, model = quantized_runner_from_checkpoint(
            rec, _build_net, example_shape=(FEAT,), calib=_CALIB,
            buckets=(1, 4))
        _scramble(model)
        qnet = build_quantized_net(model)
        prov = dict(prov, quant_digest=model.digest)
        return ModelRunner(qnet, buckets=(1, 4), example_shape=(FEAT,),
                           provenance=prov), prov

    inc_runner, inc_prov, _ = quantized_runner_from_checkpoint(
        ckpt.load_checkpoint(path), _build_net, example_shape=(FEAT,),
        calib=_CALIB, buckets=(1, 4))
    fleet = ModelFleet(batch_timeout_ms=0.5)
    fleet.register("model", inc_runner, tier_slos={"gold": 10000.0},
                   service_time_hint_ms=5.0)
    rng = np.random.RandomState(9)
    golden = rng.rand(16, FEAT).astype(np.float32)
    ctrl = PromotionController(
        fleet, "model", watch, factory, golden=golden, audit_dir=audit,
        schedule=(0.01, 0.05, 0.25), min_stage_requests=8,
        parity_threshold=0.8,
        register_kwargs={"service_time_hint_ms": 5.0})
    _train_checkpoint(0, 5, watch, "ptq-cand")
    X = rng.rand(64, FEAT).astype(np.float32)
    rid = [0]

    def pump(t):
        for _ in range(96):
            i = rid[0]
            rid[0] += 1
            try:
                fleet.infer(X[i % len(X)], model="model",
                            tier=("gold", "silver", "bronze")[i % 3],
                            request_id=i, timeout=60)
            except RequestShed:
                continue

    rec = ctrl.run(pump=pump)
    fleet.drain()
    assert rec is not None
    assert rec["decision"]["decision"] == "rollback"
    assert rec["decision"]["failed_metric"] == "golden_parity"
    assert rec["evidence"]["golden_parity"] < 0.8
    # the audit trail persisted the same story
    records = read_audit_records(audit)
    assert any(r["decision"].get("failed_metric") == "golden_parity"
               for r in records)
    # the incumbent still serves, with its quant digest intact
    stats = fleet.stats_dict()
    assert sorted(stats["models"]) == ["model"]
    assert stats["models"]["model"]["provenance"]["quant_digest"] \
        == inc_prov["quant_digest"]


def test_ptq_good_quant_passes_golden_parity():
    """The UNscrambled quantized runner is a promotable variant: golden
    parity against the f32 incumbent sits at/above the threshold."""
    from mxnet_tpu.mlops.promote import (golden_parity,
                                         runner_from_trainer_checkpoint)
    from mxnet_tpu.serving.quantize import quantized_runner_from_checkpoint
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        path = _train_checkpoint(1, 3, d, "ptq-good")
        rec = ckpt.load_checkpoint(path)
        f32_runner, _ = runner_from_trainer_checkpoint(
            rec, _build_net, example_shape=(FEAT,), buckets=(1, 4))
        q_runner, prov, model = quantized_runner_from_checkpoint(
            rec, _build_net, example_shape=(FEAT,), calib=_CALIB,
            buckets=(1, 4))
        rng = np.random.RandomState(13)
        golden = rng.rand(32, FEAT).astype(np.float32)
        assert golden_parity(f32_runner, q_runner, golden) >= 0.8
        assert prov["quant_digest"] == model.digest


# ---------------------------------------------------------------------------
# (h) capacity: int8 KV needs fewer replicas on the pinned scenario
# ---------------------------------------------------------------------------
def _capacity(kv_dtype):
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "capacity.py"),
         "--tokens", "--dau", "6500000", "--slo-ms", "300",
         "--overhead-ms", "0", "--prefill-ms", "0",
         "--max-new-tokens", "512", "--window-s", "2",
         "--kv-dtype", kv_dtype, "--json"],
        capture_output=True, text=True, cwd=_ROOT, env=_cpu_env(),
        timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    return json.loads(out.stdout)


def test_capacity_int8_kv_needs_fewer_replicas():
    """The pinned replica-drop scenario: same traffic, same SLO — the
    int8 KV pool halves the modeled per-token step time (the decode
    roofline is KV-pool-bound at this geometry) and the fleet answer
    drops a replica.  Deterministic on any host: the token_ms derives
    from the gated decode_step budget row."""
    f32 = _capacity("f32")
    i8 = _capacity("int8")
    assert f32["replicas"] == 2
    assert i8["replicas"] == 1
    assert i8["token_ms"] < f32["token_ms"] * 0.6
    assert i8["kv_dtype"] == "int8"
