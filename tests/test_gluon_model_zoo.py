"""Model zoo parity tests (reference: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo.vision import get_model

# one representative per family keeps CI fast; all 33 names are constructed
FORWARD_MODELS = ["resnet18_v1", "resnet18_v2", "mobilenet0.25",
                  "mobilenetv2_0.25", "squeezenet1.1", "alexnet"]

ALL_NAMES = [
    "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
    "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
    "resnet101_v2", "resnet152_v2", "vgg11", "vgg13", "vgg16", "vgg19",
    "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "alexnet",
    "densenet121", "densenet161", "densenet169", "densenet201",
    "squeezenet1.0", "squeezenet1.1", "inceptionv3",
    "mobilenet1.0", "mobilenet0.75", "mobilenet0.5", "mobilenet0.25",
    "mobilenetv2_1.0", "mobilenetv2_0.75", "mobilenetv2_0.5",
    "mobilenetv2_0.25",
]


def test_all_names_construct():
    for name in ALL_NAMES:
        net = get_model(name)
        assert net is not None


def test_unknown_name():
    with pytest.raises(ValueError):
        get_model("no_such_model")


@pytest.mark.parametrize("name", FORWARD_MODELS)
def test_forward(name):
    net = get_model(name, classes=10)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 224, 224).astype("float32"))
    out = net(x)
    assert out.shape == (2, 10)
    assert np.isfinite(out.asnumpy()).all()


# one per family: the SURVEY §5 race-detection analogue at model level —
# the compiled (hybridize→jit) and op-by-op executions must agree
HYBRID_MODELS = ["resnet18_v1", "resnet18_v2",
                 pytest.param("vgg11_bn", marks=pytest.mark.slow),
                 "alexnet",
                 pytest.param("densenet121", marks=pytest.mark.slow),
                 "squeezenet1.1", "mobilenet0.25",
                 "mobilenetv2_0.25"]


@pytest.mark.parametrize("name", HYBRID_MODELS)
def test_hybridize_consistency(name):
    net = get_model(name, classes=10)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 224, 224).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_thumbnail_resnet_train_smoke():
    from mxnet_tpu import autograd, gluon
    net = get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.array(np.random.rand(4, 3, 32, 32).astype("float32"))
    y = mx.nd.array(np.array([0, 1, 2, 3], dtype="float32"))
    losses = []
    for _ in range(12):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.mean().asscalar()))
    # loss must actually drop — finite-but-flat means broken grads
    assert losses[-1] < losses[0] * 0.5, losses
