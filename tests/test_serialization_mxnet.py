"""Reference .params dmlc-stream format: byte-level fixtures + round trips.

The fixture builder below packs the reference layout independently of the
library writer (reference src/ndarray/ndarray.cc:1537-1761 NDArray::Save /
Load, python/mxnet/model.py:384 arg:/aux: key prefixes), so reader and
writer are each checked against the spec, not just against each other.
"""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.ndarray.sparse import CSRNDArray, RowSparseNDArray

LIST_MAGIC = 0x112
V2_MAGIC = 0xF993FAC9
V1_MAGIC = 0xF993FAC8
NP_TO_FLAG = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
              "int32": 4, "int8": 5, "int64": 6}


def _tshape(shape):
    return struct.pack("<I", len(shape)) + \
        struct.pack("<%dq" % len(shape), *shape)


def _dense_record(a):
    a = np.ascontiguousarray(a)
    return (struct.pack("<I", V2_MAGIC) + struct.pack("<i", 0) +
            _tshape(a.shape) + struct.pack("<ii", 1, 0) +
            struct.pack("<i", NP_TO_FLAG[a.dtype.name]) + a.tobytes())


def _fixture_bytes(named_arrays, records=None):
    names = list(named_arrays.keys())
    recs = records or [_dense_record(a) for a in named_arrays.values()]
    out = struct.pack("<QQ", LIST_MAGIC, 0) + struct.pack("<Q", len(recs))
    out += b"".join(recs)
    out += struct.pack("<Q", len(names))
    for n in names:
        b = n.encode()
        out += struct.pack("<Q", len(b)) + b
    return out


@pytest.mark.smoke
def test_reference_fixture_loads(tmp_path):
    arrays = {
        "arg:fc1_weight": np.random.randn(4, 3).astype(np.float32),
        "arg:fc1_bias": np.arange(4, dtype=np.float64),
        "aux:bn_mean": np.random.rand(3).astype(np.float16),
        "arg:idx": np.array([1, 2, 7], np.int64),
        "arg:bytes": np.array([[0, 255], [7, 9]], np.uint8),
    }
    p = tmp_path / "ref.params"
    p.write_bytes(_fixture_bytes(arrays))
    loaded = nd.load(str(p))
    assert set(loaded) == set(arrays)
    # jax (x64 off) narrows 64-bit dtypes on device; values must survive
    narrowed = {"float64": "float32", "int64": "int32"}
    for k, v in arrays.items():
        got = loaded[k].asnumpy()
        want_dt = narrowed.get(v.dtype.name, v.dtype.name)
        assert got.dtype.name == want_dt and got.shape == v.shape
        np.testing.assert_array_equal(got, v.astype(want_dt))


def test_reference_fixture_list_no_names(tmp_path):
    a = np.random.randn(2, 2).astype(np.float32)
    raw = struct.pack("<QQQ", LIST_MAGIC, 0, 1) + _dense_record(a) + \
        struct.pack("<Q", 0)
    p = tmp_path / "anon.params"
    p.write_bytes(raw)
    loaded = nd.load(str(p))
    assert isinstance(loaded, list) and len(loaded) == 1
    np.testing.assert_array_equal(loaded[0].asnumpy(), a)


def test_legacy_v1_and_pre_v1_records(tmp_path):
    """LegacyLoad (ndarray.cc:1619): V1 = int64 TShape after magic;
    pre-V1 = the magic word is ndim, dims are uint32."""
    a = np.random.randn(3, 2).astype(np.float32)
    v1 = (struct.pack("<I", V1_MAGIC) + _tshape(a.shape) +
          struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a.tobytes())
    pre = (struct.pack("<I", 2) + struct.pack("<II", 3, 2) +
           struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a.tobytes())
    p = tmp_path / "legacy.params"
    p.write_bytes(_fixture_bytes({"arg:v1": a, "arg:pre": a},
                                 records=[v1, pre]))
    loaded = nd.load(str(p))
    np.testing.assert_array_equal(loaded["arg:v1"].asnumpy(), a)
    np.testing.assert_array_equal(loaded["arg:pre"].asnumpy(), a)


def test_sparse_fixture_loads(tmp_path):
    """V2 sparse records: row_sparse (aux=[row idx]) and csr
    (aux=[indptr, col idx]) — ndarray.cc:1546-1600."""
    vals = np.array([[1., 2.], [3., 4.]], np.float32)
    idx = np.array([0, 3], np.int64)
    rsp = (struct.pack("<I", V2_MAGIC) + struct.pack("<i", 1) +
           _tshape(vals.shape) + _tshape((4, 2)) +
           struct.pack("<ii", 1, 0) + struct.pack("<i", 0) +
           struct.pack("<i", 6) + _tshape(idx.shape) +
           vals.tobytes() + idx.tobytes())
    data = np.array([5., 6., 7.], np.float32)
    indptr = np.array([0, 2, 2, 3], np.int64)
    col = np.array([0, 2, 1], np.int64)
    csr = (struct.pack("<I", V2_MAGIC) + struct.pack("<i", 2) +
           _tshape(data.shape) + _tshape((3, 3)) +
           struct.pack("<ii", 1, 0) + struct.pack("<i", 0) +
           struct.pack("<i", 6) + _tshape(indptr.shape) +
           struct.pack("<i", 6) + _tshape(col.shape) +
           data.tobytes() + indptr.tobytes() + col.tobytes())
    p = tmp_path / "sparse.params"
    p.write_bytes(_fixture_bytes({"arg:rsp": None, "arg:csr": None},
                                 records=[rsp, csr]))
    loaded = nd.load(str(p))
    assert isinstance(loaded["arg:rsp"], RowSparseNDArray)
    dense = np.zeros((4, 2), np.float32)
    dense[[0, 3]] = vals
    np.testing.assert_array_equal(loaded["arg:rsp"].asnumpy(), dense)
    assert isinstance(loaded["arg:csr"], CSRNDArray)
    want = np.array([[5., 0., 6.], [0., 0., 0.], [0., 7., 0.]], np.float32)
    np.testing.assert_array_equal(loaded["arg:csr"].asnumpy(), want)


@pytest.mark.smoke
def test_writer_matches_fixture_bytes(tmp_path):
    """The mxnet-format writer must produce the spec bytes, not merely
    bytes its own reader accepts."""
    arrays = {"arg:w": np.random.randn(2, 3).astype(np.float32),
              "aux:m": np.arange(6, dtype=np.int32)}
    p = tmp_path / "w.params"
    nd.save(str(p), {k: mx.nd.array(v, dtype=v.dtype)
                     for k, v in arrays.items()}, format="mxnet")
    assert p.read_bytes() == _fixture_bytes(arrays)


def test_writer_reader_roundtrip_sparse(tmp_path):
    rsp = mx.nd.sparse.row_sparse_array(
        (np.array([[1., 2.]], np.float32), np.array([2], np.int64)),
        shape=(5, 2))
    p = tmp_path / "rt.params"
    nd.save(str(p), {"arg:g": rsp}, format="mxnet")
    back = nd.load(str(p))["arg:g"]
    assert isinstance(back, RowSparseNDArray)
    np.testing.assert_array_equal(back.asnumpy(), rsp.asnumpy())


def test_bf16_widens_to_f32_in_mxnet_format(tmp_path):
    x = mx.nd.array(np.random.randn(3, 3).astype(np.float32)) \
        .astype("bfloat16")
    p = tmp_path / "bf16.params"
    nd.save(str(p), {"arg:w": x}, format="mxnet")
    back = nd.load(str(p))["arg:w"]
    assert back.dtype == np.float32
    np.testing.assert_allclose(back.asnumpy(),
                               x.asnumpy().astype(np.float32))


@pytest.mark.smoke
def test_gluon_load_parameters_from_reference_params(tmp_path):
    """A reference-format zoo checkpoint imports through
    Block.load_parameters (VERDICT r4 item 2's done condition)."""
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(4, in_units=3), gluon.nn.Dense(2, in_units=4))
    net.initialize(mx.init.Xavier())
    names = list(net._collect_params_with_prefix())
    arrays = {n: np.random.randn(
        *net._collect_params_with_prefix()[n].shape).astype(np.float32)
        for n in names}
    p = tmp_path / "net.params"
    p.write_bytes(_fixture_bytes(arrays))
    net.load_parameters(str(p))
    for n, want in arrays.items():
        got = net._collect_params_with_prefix()[n].data().asnumpy()
        np.testing.assert_array_equal(got, want)
    # and the gluon writer round-trips through the same reference format
    p2 = tmp_path / "net2.params"
    net.save_parameters(str(p2), format="mxnet")
    net2 = gluon.nn.Sequential()
    net2.add(gluon.nn.Dense(4, in_units=3), gluon.nn.Dense(2, in_units=4))
    net2.load_parameters(str(p2))
    for n, want in arrays.items():
        got = net2._collect_params_with_prefix()[n].data().asnumpy()
        np.testing.assert_array_equal(got, want)


def test_model_checkpoint_reference_format(tmp_path):
    """save_checkpoint(format="mxnet") + load_checkpoint round trip with
    arg:/aux: prefixes (reference model.py:384)."""
    x = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    arg = {"fc_weight": mx.nd.array(np.random.randn(2, 3)),
           "fc_bias": mx.nd.array(np.zeros(2, np.float32))}
    aux = {"stat": mx.nd.array(np.ones(2, np.float32))}
    prefix = str(tmp_path / "ckpt")
    mx.model.save_checkpoint(prefix, 3, net, arg, aux, format="mxnet")
    # byte-level: the file must carry the reference list magic
    with open(prefix + "-0003.params", "rb") as f:
        assert struct.unpack("<Q", f.read(8))[0] == LIST_MAGIC
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 3)
    assert set(arg2) == set(arg) and set(aux2) == set(aux)
    for k in arg:
        np.testing.assert_array_equal(arg2[k].asnumpy(), arg[k].asnumpy())
    np.testing.assert_array_equal(aux2["stat"].asnumpy(),
                                  aux["stat"].asnumpy())


def test_scalar_widens_to_shape1(tmp_path):
    """0-d arrays widen to (1,) — the reference format has no 0-d (a
    zero-ndim shape marks a 'none' array, ndarray.cc:1554), and a naive
    full record after ndim=0 would desync every later record."""
    p = tmp_path / "scalar.params"
    nd.save(str(p), {"arg:w": mx.nd.array(np.float32(3.5)),
                     "arg:after": mx.nd.array(np.arange(2, dtype=np.float32))},
            format="mxnet")
    loaded = nd.load(str(p))
    assert loaded["arg:w"].shape == (1,)
    np.testing.assert_array_equal(loaded["arg:w"].asnumpy(), [3.5])
    np.testing.assert_array_equal(loaded["arg:after"].asnumpy(), [0., 1.])


def test_bad_magic_rejected(tmp_path):
    p = tmp_path / "junk.params"
    p.write_bytes(b"\x00" * 64)
    with pytest.raises(ValueError):
        nd.load(str(p))


def test_bool_saved_as_uint8_not_flag7(tmp_path):
    """ADVICE r5: flag 7 (bool) is load-side only — the targeted stock
    MXNet dtype table stops at 6, so saving bool with format="mxnet" must
    cast to uint8 (flag 3), value-preserving, instead of emitting an
    unloadable record."""
    p = str(tmp_path / "bool.params")
    mask = np.array([True, False, True, True])
    nd.save(p, {"arg:mask": mask}, format="mxnet")
    raw = open(p, "rb").read()
    assert struct.pack("<i", 7) not in raw          # no flag 7 on the wire
    assert struct.pack("<i", NP_TO_FLAG["uint8"]) in raw
    loaded = nd.load(p)
    got = loaded["arg:mask"].asnumpy()
    assert got.dtype == np.uint8
    np.testing.assert_array_equal(got, [1, 0, 1, 1])


def test_flag7_bool_record_still_loads(tmp_path):
    """Newer producers that do write flag 7 stay loadable (accept-on-load
    half of the contract)."""
    vals = np.array([1, 0, 1, 0], np.uint8)          # bool itemsize == 1
    rec = (struct.pack("<I", V2_MAGIC) + struct.pack("<i", 0) +
           _tshape((4,)) + struct.pack("<ii", 1, 0) +
           struct.pack("<i", 7) + vals.tobytes())
    p = tmp_path / "flag7.params"
    p.write_bytes(_fixture_bytes({"arg:m": None}, records=[rec]))
    got = nd.load(str(p))["arg:m"].asnumpy()
    np.testing.assert_array_equal(got.astype(np.uint8), vals)
