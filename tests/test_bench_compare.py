"""tools/bench_compare.py: the BENCH_r*.json lineage as a regression
gate (tier-1, ISSUE 10 satellite).

Contract points: the shipped r01..r05 lineage passes (staleness
protocol honored — r05's carried-forward keys set no bar); a
synthetically injected regression in a copied BENCH file exits nonzero
and names the metric; a malformed record fails fast; the gate math
(direction, relative vs absolute tolerance, no-prior vacuous pass) is
pinned at the function level.
"""
import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_TOOL = os.path.join(_ROOT, "tools", "bench_compare.py")


def _load_tool():
    spec = importlib.util.spec_from_file_location("_bench_compare", _TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bc = _load_tool()
_LINEAGE = sorted(
    os.path.join(_ROOT, f) for f in os.listdir(_ROOT)
    if f.startswith("BENCH_r") and f.endswith(".json"))


def test_real_lineage_passes_check():
    """The tier-1 CI wiring: the shipped bench history must gate clean
    (a regressing or malformed BENCH file in a PR fails this test)."""
    assert _LINEAGE, "no BENCH_r*.json lineage on disk"
    out = subprocess.run(
        [sys.executable, _TOOL, "--check"] + _LINEAGE,
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "bench lineage ok" in out.stdout


def test_staleness_protocol_sets_no_bar():
    """r05 re-emits r02's numbers as carry-forwards (stale/stale_keys);
    they must count as neither newest-live nor best-prior."""
    report = bc.compare(_LINEAGE)
    gates = report["gates"]
    # pipeline_fed was live ONLY in r02 (r05's copy is stale) -> no bar
    assert gates["pipeline_fed_imgs_per_sec"]["verdict"] == "no-prior"
    assert gates["pipeline_fed_imgs_per_sec"]["live_rounds"] == [2]
    # the primary metric was live in r01 and r02, r02 improved
    assert gates["value"]["verdict"] == "ok"
    assert gates["value"]["live_rounds"] == [1, 2]
    assert report["regressions"] == [] and report["malformed"] == []


def test_injected_regression_detected(tmp_path):
    """The acceptance criterion: copy a BENCH file, regress one gated
    metric -> exit nonzero, metric named."""
    for f in _LINEAGE:
        shutil.copy(f, tmp_path)
    rec = json.load(open(os.path.join(_ROOT, "BENCH_r02.json")))
    rec["parsed"]["pipeline_fed_imgs_per_sec"] = 50.0   # was 126.93 live
    rec["n"] = 6
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump(rec, f)
    files = sorted(str(p) for p in tmp_path.glob("BENCH_r*.json"))
    out = subprocess.run([sys.executable, _TOOL] + files,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, out.stdout
    assert "REGRESSION" in out.stdout
    assert "pipeline_fed_imgs_per_sec" in out.stdout
    # an improvement (or within-tolerance dip) stays green
    rec["parsed"]["pipeline_fed_imgs_per_sec"] = 120.0  # -5.5% < 10% tol
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump(rec, f)
    out = subprocess.run([sys.executable, _TOOL] + files,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout


def test_malformed_record_fails_fast(tmp_path):
    bad = tmp_path / "BENCH_r09.json"
    bad.write_text("{torn mid-write")
    out = subprocess.run([sys.executable, _TOOL, str(bad)],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "MALFORMED" in out.stdout
    # structurally wrong (missing record keys) is malformed too
    bad.write_text(json.dumps({"unexpected": 1}))
    out = subprocess.run([sys.executable, _TOOL, str(bad)],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 1, out.stdout
    with pytest.raises(bc.MalformedRecord):
        bc.load_record(str(bad))


def test_fusion_keys_gated(tmp_path):
    """The r06 fusion-stage keys gate like any other: a slower fused
    update, a thinner modeled win, or a numerics drop all regress; the
    zero-slack numerics gate bites on ANY drop from 1.0."""
    def rec(n, parsed):
        return {"n": n, "cmd": "bench", "rc": 0, "tail": "",
                "parsed": parsed}
    a = tmp_path / "BENCH_r06.json"
    b = tmp_path / "BENCH_r07.json"
    base = {"fused_optimizer_speedup_host": 2.2,
            "modeled_fusion_bytes_saved_pct": 70.6,
            "fusion_numerics_ok": 1.0}
    a.write_text(json.dumps(rec(6, base)))
    b.write_text(json.dumps(rec(7, dict(base))))
    report = bc.compare([str(a), str(b)])
    assert report["regressions"] == []
    # speedup collapse past 10% regresses
    b.write_text(json.dumps(rec(7, dict(base,
                                        fused_optimizer_speedup_host=1.5))))
    report = bc.compare([str(a), str(b)])
    assert report["regressions"] == ["fused_optimizer_speedup_host"]
    # modeled bytes-saved is near-deterministic: 2% rel
    b.write_text(json.dumps(rec(
        7, dict(base, modeled_fusion_bytes_saved_pct=60.0))))
    report = bc.compare([str(a), str(b)])
    assert report["regressions"] == ["modeled_fusion_bytes_saved_pct"]
    # numerics: zero slack — any drop from 1.0 regresses
    b.write_text(json.dumps(rec(7, dict(base, fusion_numerics_ok=0.0))))
    report = bc.compare([str(a), str(b)])
    assert report["regressions"] == ["fusion_numerics_ok"]


def test_precision_keys_gated(tmp_path):
    """The r08 precision-stage keys gate like any other: a slower
    fused loss-scaled update, a fatter modeled bf16/f32 HBM ratio, a
    widening bf16 convergence gap, slower int8-KV decode, or a
    numerics drop all regress — the abs-slack gates bite past their
    documented slack, the zero-slack one on ANY drop from 1.0."""
    def rec(n, parsed):
        return {"n": n, "cmd": "bench", "rc": 0, "tail": "",
                "parsed": parsed}
    a = tmp_path / "BENCH_r08.json"
    b = tmp_path / "BENCH_r09.json"
    base = {"fused_loss_scaled_speedup_host": 2.5,
            "bf16_modeled_hbm_ratio": 0.66,
            "bf16_convergence_delta": 0.006,
            "int8_kv_decode_tokens_per_sec_host": 2200.0,
            "precision_numerics_ok": 1.0}
    a.write_text(json.dumps(rec(8, base)))
    b.write_text(json.dumps(rec(9, dict(base))))
    report = bc.compare([str(a), str(b)])
    assert report["regressions"] == []
    # fused loss-scaled speedup collapse past 10% regresses
    b.write_text(json.dumps(rec(
        9, dict(base, fused_loss_scaled_speedup_host=1.8))))
    assert bc.compare([str(a), str(b)])["regressions"] == [
        "fused_loss_scaled_speedup_host"]
    # modeled HBM ratio creeping up past the 0.02 abs slack regresses
    # (the f32 masters leaking out of the shard looks exactly like this)
    b.write_text(json.dumps(rec(
        9, dict(base, bf16_modeled_hbm_ratio=0.75))))
    assert bc.compare([str(a), str(b)])["regressions"] == [
        "bf16_modeled_hbm_ratio"]
    # a widening bf16-vs-f32 trajectory gap past +0.005 regresses
    b.write_text(json.dumps(rec(
        9, dict(base, bf16_convergence_delta=0.05))))
    assert bc.compare([str(a), str(b)])["regressions"] == [
        "bf16_convergence_delta"]
    # int8-KV decode throughput collapse past 10% regresses
    b.write_text(json.dumps(rec(
        9, dict(base, int8_kv_decode_tokens_per_sec_host=1500.0))))
    assert bc.compare([str(a), str(b)])["regressions"] == [
        "int8_kv_decode_tokens_per_sec_host"]
    # numerics: zero slack — any drop from 1.0 regresses
    b.write_text(json.dumps(rec(
        9, dict(base, precision_numerics_ok=0.0))))
    assert bc.compare([str(a), str(b)])["regressions"] == [
        "precision_numerics_ok"]


def test_gate_math_directions(tmp_path):
    """lower_abs gates (overhead pcts near zero) use absolute slack;
    higher gates use relative tolerance."""
    def rec(n, parsed):
        return {"n": n, "cmd": "bench", "rc": 0, "tail": "",
                "parsed": parsed}
    a = tmp_path / "BENCH_r01.json"
    b = tmp_path / "BENCH_r02.json"
    a.write_text(json.dumps(rec(1, {"telemetry_overhead_pct": 0.5,
                                    "serving_reqs_per_sec": 100.0})))
    # overhead 0.5 -> 0.9 is within +0.5 abs slack; reqs/s -15% is not
    b.write_text(json.dumps(rec(2, {"telemetry_overhead_pct": 0.9,
                                    "serving_reqs_per_sec": 85.0})))
    report = bc.compare([str(a), str(b)])
    assert report["gates"]["telemetry_overhead_pct"]["verdict"] == "ok"
    assert report["gates"]["serving_reqs_per_sec"]["verdict"] == \
        "regression"
    assert report["regressions"] == ["serving_reqs_per_sec"]
    # overhead past the absolute slack regresses
    b.write_text(json.dumps(rec(2, {"telemetry_overhead_pct": 1.2,
                                    "serving_reqs_per_sec": 100.0})))
    report = bc.compare([str(a), str(b)])
    assert report["regressions"] == ["telemetry_overhead_pct"]
    # --tolerance-scale widens every gate
    report = bc.compare([str(a), str(b)], tolerance_scale=2.0)
    assert report["regressions"] == []
