"""contrib.text parity: embedding loaders, registry, composite
(reference: tests/python/unittest/test_contrib_text.py + the
embedding.py catalog/downloader contract).  The hosted-download path is
driven offline through a file:// repo (MXNET_GLUON_REPO override),
exercising the real fetch + sha1-verify + unzip + load chain."""
import hashlib
import os
import zipfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text
from mxnet_tpu.gluon.utils import check_sha1, download


def _sha1(path):
    h = hashlib.sha1()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def _write_vec_file(path, rows, header=None, delim=" "):
    with open(path, "w") as f:
        if header:
            f.write(header + "\n")
        for tok, vec in rows:
            f.write(tok + delim + delim.join(str(v) for v in vec) + "\n")


# ---------------------------------------------------------------------------
# CustomEmbedding semantics
# ---------------------------------------------------------------------------
def test_custom_embedding_loads_and_indexes(tmp_path):
    p = tmp_path / "emb.txt"
    _write_vec_file(p, [("hello", [1, 2]), ("world", [3, 4])])
    emb = text.CustomEmbedding(str(p))
    assert emb.vec_len == 2
    assert len(emb) == 3  # <unk> + 2 tokens
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [3, 4])
    # unknown token maps to index 0 (zeros by default)
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("nope").asnumpy(), [0, 0])
    # batch lookup keeps order
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens(["world", "hello"]).asnumpy(),
        [[3, 4], [1, 2]])


def test_custom_embedding_duplicate_and_header_rows(tmp_path):
    p = tmp_path / "emb.txt"
    _write_vec_file(p, [("a", [1, 1]), ("a", [9, 9]), ("b", [2, 2])],
                    header="2 2")
    with pytest.warns(UserWarning):
        emb = text.CustomEmbedding(str(p))
    # header skipped, first duplicate wins
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("a").asnumpy(), [1, 1])
    assert "2" not in emb.token_to_idx


def test_custom_embedding_unknown_token_vector_from_file(tmp_path):
    p = tmp_path / "emb.txt"
    _write_vec_file(p, [("<unk>", [7, 7]), ("a", [1, 1])])
    emb = text.CustomEmbedding(str(p))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("missing").asnumpy(), [7, 7])


def test_custom_embedding_with_vocabulary(tmp_path):
    p = tmp_path / "emb.txt"
    _write_vec_file(p, [("a", [1, 1]), ("b", [2, 2]), ("c", [3, 3])])
    counter = text.count_tokens_from_str("a b b zzz")
    vocab = text.Vocabulary(counter)
    emb = text.CustomEmbedding(str(p), vocabulary=vocab)
    # only vocab tokens are indexed; zzz has no pretrained vector
    assert set(emb.token_to_idx) == {"<unk>", "a", "b", "zzz"}
    np.testing.assert_allclose(
        emb.idx_to_vec.asnumpy()[emb.token_to_idx["zzz"]], [0, 0])
    np.testing.assert_allclose(
        emb.idx_to_vec.asnumpy()[emb.token_to_idx["b"]], [2, 2])
    assert "c" not in emb.token_to_idx


def test_update_token_vectors(tmp_path):
    p = tmp_path / "emb.txt"
    _write_vec_file(p, [("a", [1, 1]), ("b", [2, 2])])
    emb = text.CustomEmbedding(str(p))
    emb.update_token_vectors("a", mx.nd.array([5.0, 6.0]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("a").asnumpy(), [5, 6])
    with pytest.raises(ValueError):
        emb.update_token_vectors("unseen", mx.nd.array([1.0, 1.0]))
    # updating the unknown vector requires naming it explicitly
    emb.update_token_vectors("<unk>", mx.nd.array([9.0, 9.0]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("unseen").asnumpy(), [9, 9])


def test_lower_case_backup(tmp_path):
    p = tmp_path / "emb.txt"
    _write_vec_file(p, [("hello", [1, 2])])
    emb = text.CustomEmbedding(str(p))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("HELLO", lower_case_backup=True).asnumpy(),
        [1, 2])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("HELLO").asnumpy(), [0, 0])


# ---------------------------------------------------------------------------
# CompositeEmbedding
# ---------------------------------------------------------------------------
def test_composite_embedding_concatenates(tmp_path):
    p1, p2 = tmp_path / "e1.txt", tmp_path / "e2.txt"
    _write_vec_file(p1, [("a", [1, 1]), ("b", [2, 2])])
    _write_vec_file(p2, [("b", [30, 30, 30]), ("c", [40, 40, 40])])
    e1 = text.CustomEmbedding(str(p1))
    e2 = text.CustomEmbedding(str(p2))
    vocab = text.Vocabulary(text.count_tokens_from_str("a b c"))
    comp = text.CompositeEmbedding(vocab, [e1, e2])
    assert comp.vec_len == 5
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("b").asnumpy(), [2, 2, 30, 30, 30])
    # a: present only in e1; c: only in e2 - missing halves are zeros
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("a").asnumpy(), [1, 1, 0, 0, 0])
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("c").asnumpy(), [0, 0, 40, 40, 40])


# ---------------------------------------------------------------------------
# registry + hosted-catalog path over file:// (offline-testable)
# ---------------------------------------------------------------------------
def test_registry_create_and_catalog():
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    assert "glove.6B.50d.txt" in \
        text.embedding.get_pretrained_file_names("glove")
    with pytest.raises(KeyError):
        text.GloVe(pretrained_file_name="not_in_catalog.txt")


@text.embedding.register
class TinyTestEmbedding(text.embedding.TokenEmbedding):
    """Catalog-driven embedding served from a file:// repo."""

    pretrained_file_name_sha1 = {}  # filled by the test
    pretrained_archive_name_sha1 = {}

    @classmethod
    def _get_download_file_name(cls, pretrained_file_name):
        return os.path.splitext(pretrained_file_name)[0] + ".zip"

    def __init__(self, pretrained_file_name="tiny.vec",
                 embedding_root="~/.mxnet_tpu/embeddings",
                 init_unknown_vec=mx.nd.zeros, vocabulary=None, **kw):
        self._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kw)
        path = self._get_pretrained_file(embedding_root,
                                         pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        self._build_embedding_for_vocabulary(vocabulary)


def test_hosted_embedding_download_verify_extract(tmp_path, monkeypatch):
    # build the "hosted" repo: a zip containing tiny.vec
    repo = tmp_path / "repo" / "gluon" / "embeddings" / "tinytestembedding"
    repo.mkdir(parents=True)
    vec = tmp_path / "tiny.vec"
    _write_vec_file(vec, [("a", [1, 2, 3]), ("b", [4, 5, 6])],
                    header="2 3")
    zpath = repo / "tiny.zip"
    with zipfile.ZipFile(zpath, "w") as zf:
        zf.write(vec, "tiny.vec")
    # extracted-file sha1 + archive sha1, like the real catalogs
    TinyTestEmbedding.pretrained_file_name_sha1 = {
        "tiny.vec": _sha1(str(vec))}
    TinyTestEmbedding.pretrained_archive_name_sha1 = {
        "tiny.zip": _sha1(str(zpath))}
    monkeypatch.setenv("MXNET_GLUON_REPO",
                       "file://" + str(tmp_path / "repo") + "/")

    root = tmp_path / "cache"
    with pytest.warns(UserWarning):  # the .vec header row is skipped
        emb = text.embedding.create("tinytestembedding",
                                    pretrained_file_name="tiny.vec",
                                    embedding_root=str(root))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("b").asnumpy(), [4, 5, 6])
    # the extracted file landed under root/<cls>/ and verifies
    cached = root / "tinytestembedding" / "tiny.vec"
    assert cached.exists()
    assert check_sha1(str(cached),
                      TinyTestEmbedding.pretrained_file_name_sha1
                      ["tiny.vec"])
    # second construction hits the verified cache (no re-download):
    # poison the repo and make sure loading still works
    zpath.unlink()
    emb2 = TinyTestEmbedding(pretrained_file_name="tiny.vec",
                             embedding_root=str(root))
    assert emb2.vec_len == 3


# ---------------------------------------------------------------------------
# gluon.utils.download (reference: gluon/utils.py:178)
# ---------------------------------------------------------------------------
def test_download_sha1_verify_and_retry(tmp_path):
    src = tmp_path / "src.bin"
    src.write_bytes(b"payload")
    url = "file://" + str(src)
    dst = tmp_path / "out" / "dst.bin"
    got = download(url, str(dst), sha1_hash=_sha1(str(src)))
    assert got == str(dst) and dst.read_bytes() == b"payload"
    # wrong hash: retried, then raises; no trusted file left behind
    bad = tmp_path / "bad.bin"
    with pytest.raises(IOError):
        download(url, str(bad), sha1_hash="0" * 40, retries=1)
    # existing verified file short-circuits even if the source vanishes
    src.unlink()
    assert download(url, str(dst), sha1_hash=_sha1(str(dst))) == str(dst)


def test_download_missing_source_retries_then_raises(tmp_path):
    with pytest.raises(IOError):
        download("file://" + str(tmp_path / "ghost"),
                 str(tmp_path / "o.bin"), retries=2)


def test_reserved_tokens_keep_vectors_aligned(tmp_path):
    """reserved_tokens shift every file token's index; the vector table
    must shift with them (regression: r4 review)."""
    p = tmp_path / "emb.txt"
    _write_vec_file(p, [("a", [1, 1]), ("b", [2, 2])])
    emb = text.CustomEmbedding(str(p), reserved_tokens=["<pad>", "<bos>"])
    assert emb.to_indices("a") == 3  # unk, <pad>, <bos>, a, b
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("a").asnumpy(), [1, 1])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("b").asnumpy(), [2, 2])
    # reserved tokens carry the init vector (zeros)
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("<pad>").asnumpy(), [0, 0])


def test_fasttext_catalog_archives_complete():
    """Every advertised fastText file must map to a sha1-cataloged
    archive (regression: r4 review - wiki.en.vec KeyError)."""
    from mxnet_tpu.contrib.text import embedding as emb_mod
    for f in text.embedding.get_pretrained_file_names("fasttext"):
        archive = text.FastText._get_download_file_name(f)
        assert archive in text.FastText.pretrained_archive_name_sha1, f
    for f in text.embedding.get_pretrained_file_names("glove"):
        archive = text.GloVe._get_download_file_name(f)
        assert archive in text.GloVe.pretrained_archive_name_sha1, f
