"""mxnet_tpu.analysis (mxlint) — registry, graph and source passes.

Every rule_id fires at least once on a crafted fixture and stays silent
on a clean op/graph; the self-check CLI (what CI runs) passes on the
shipped registry.
"""
import functools
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym

pytestmark = pytest.mark.analysis
from mxnet_tpu.analysis import (lint_graph, lint_registry, lint_source,
                                render_json, render_text, exit_code)
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ops import registry
from mxnet_tpu.symbol.symbol import Symbol, _sym_invoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules(findings):
    return {f.rule_id for f in findings}


# ---------------------------------------------------------------------------
# registry pass — against an isolated fake registry (the real one must stay
# clean, which test_self_check_cli proves)
# ---------------------------------------------------------------------------
class FakeReg:
    def __init__(self):
        self._ops = {}
        self._shadows = []

    def add(self, op, *names):
        for n in (op.name,) + names:
            self._ops[n] = op
        return op

    def list_ops(self):
        return sorted(self._ops)

    def get(self, name):
        return self._ops[name]

    def shadowed(self):
        return list(self._shadows)


def _good_fn(data, weight, alpha=1.0):
    """A well-formed fixture op."""
    return data * weight * alpha


def test_clean_op_is_silent():
    reg = FakeReg()
    reg.add(registry.Op("good", _good_fn, arg_names=["data", "weight"],
                        scalar_args=("alpha",)))
    assert lint_registry(registry=reg) == []


def test_reg001_missing_tensor_slot():
    reg = FakeReg()
    reg.add(registry.Op("bad", lambda data: data,
                        arg_names=["data", "weight"],
                        doc="fn has one positional param, two slots."))
    assert rules(lint_registry(registry=reg)) == {"REG001"}


def test_reg001_variadic_without_star_args():
    reg = FakeReg()
    reg.add(registry.Op("badvar", lambda data: data, arg_names=["args"],
                        doc="variadic registration over a unary fn."))
    assert "REG001" in rules(lint_registry(registry=reg))


def test_reg002_slot_order_swap():
    reg = FakeReg()
    reg.add(registry.Op("swapped", lambda weight, data: data @ weight,
                        arg_names=["data", "weight"],
                        doc="slots transposed vs fn params."))
    assert rules(lint_registry(registry=reg)) == {"REG002"}


def test_reg003_unknown_scalar_arg():
    reg = FakeReg()
    reg.add(registry.Op("badscalar", lambda data: data,
                        scalar_args=("alpha",),
                        doc="alpha is not a parameter of fn."))
    assert rules(lint_registry(registry=reg)) == {"REG003"}


def test_reg004_unknown_optional_arg():
    reg = FakeReg()
    reg.add(registry.Op("badopt", lambda data, bias=None: data,
                        arg_names=["data", "bias"],
                        optional_args=("nonexistent",),
                        doc="optional names no slot."))
    assert rules(lint_registry(registry=reg)) == {"REG004"}


def test_reg005_aux_index_gap():
    reg = FakeReg()
    reg.add(registry.Op("badaux",
                        lambda data, gamma, mean=None, var=None: data,
                        arg_names=["data", "gamma"],
                        aux={3: "mean", 4: "var"},   # should start at 2
                        doc="aux range leaves a hole at index 2."))
    assert rules(lint_registry(registry=reg)) == {"REG005"}


def test_reg006_mutates_out_of_range():
    reg = FakeReg()
    reg.add(registry.Op("badmut", lambda w, g: (w, w - g),
                        arg_names=["weight", "grad"], mutates={5: 1},
                        doc="mutated input index 5 does not exist."))
    assert rules(lint_registry(registry=reg)) == {"REG006"}


def test_reg007_num_outputs_not_total():
    reg = FakeReg()
    reg.add(registry.Op("badnout", lambda data: data,
                        num_outputs=lambda p: p["k"],   # KeyError on {}
                        doc="num_outputs requires an undefaulted param."))
    assert rules(lint_registry(registry=reg)) == {"REG007"}


def test_reg008_alias_shadow():
    reg = FakeReg()
    a = reg.add(registry.Op("first", lambda data: data, doc="original."))
    reg.add(registry.Op("second", lambda data: -data, doc="usurper."))
    reg._shadows.append(("first", "first", "second"))
    assert "REG008" in rules(lint_registry(registry=reg))


def test_register_records_shadows():
    before = list(registry.shadowed())
    ops_before = dict(registry._OPS)
    try:
        registry.register("_lintfix_shadow_victim",
                          doc="victim.")(lambda data: data)
        registry.register("_lintfix_other",
                          aliases=("_lintfix_shadow_victim",),
                          doc="shadows the victim via alias.")(
                              lambda data: -data)
        new = [s for s in registry.shadowed() if s not in before]
        assert ("_lintfix_shadow_victim", "_lintfix_shadow_victim",
                "_lintfix_other") in new
    finally:
        registry._OPS.clear()
        registry._OPS.update(ops_before)
        registry._SHADOWS[:] = before


def test_reg009_missing_docstring_and_suppression():
    reg = FakeReg()
    reg.add(registry.Op("nodoc", lambda data: data))
    assert rules(lint_registry(registry=reg)) == {"REG009"}

    def suppressed_fn(data):
        # mxlint: disable=REG009
        return data

    reg2 = FakeReg()
    reg2.add(registry.Op("nodoc2", suppressed_fn))
    assert lint_registry(registry=reg2) == []


def test_reg010_zero_coverage():
    reg = FakeReg()
    reg.add(registry.Op("uncovered", lambda data: data, doc="fixture."))
    assert rules(lint_registry(registry=reg, coverage_map={})) == {"REG010"}
    # an alias entry in the map covers the canonical name too
    reg.add(reg.get("uncovered"), "uncovered_alias")
    assert lint_registry(
        registry=reg,
        coverage_map={"uncovered_alias": "somewhere"}) == []


def test_reg011_introspection_fallback():
    class Weird:
        __signature__ = "not-a-signature"

        def __call__(self, data):
            return data

    reg = FakeReg()
    reg.add(registry.Op("weird", Weird(), doc="uninspectable callable."))
    assert "REG011" in rules(lint_registry(registry=reg))


def test_fn_params_robust_to_partial():
    def base(data, other, alpha=1.0, beta=2.0):
        """Partial-registered fixture."""
        return data + other * alpha * beta

    op = registry.Op("partial_op", functools.partial(base, beta=3.0),
                     arg_names=["data", "other"], scalar_args=("alpha",))
    assert op.fn_params == ["data", "other", "alpha"]
    assert not op.fn_params_fallback
    reg = FakeReg()
    reg.add(op)
    assert lint_registry(registry=reg) == []


# ---------------------------------------------------------------------------
# graph pass
# ---------------------------------------------------------------------------
def test_grf001_dead_output():
    data = sym.var("data")
    parts = sym.SliceChannel(data, num_outputs=3, name="dead_split")
    findings = lint_graph(parts[0], check_consts=False)
    assert [f.rule_id for f in findings] == ["GRF001", "GRF001"]
    # consuming every output silences the rule
    s = parts[0] + parts[1] + parts[2]
    assert lint_graph(s, check_consts=False) == []


def test_grf002_nondiff_on_grad_path():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="g2_fc")
    cut = sym.argmax(fc, axis=1, name="g2_argmax")
    loss = sym.MakeLoss(cut, name="g2_loss")
    findings = lint_graph(loss, check_consts=False)
    assert rules(findings) == {"GRF002"}
    assert findings[0].subject == "g2_argmax"
    # no loss head -> predict-only graph, rule stays quiet
    assert lint_graph(cut, check_consts=False) == []
    # differentiable path to the loss head is fine
    assert lint_graph(sym.MakeLoss(fc, name="g2_ok"),
                      check_consts=False) == []


def test_grf003_aux_read_outside_train():
    data = sym.var("data")
    bn = sym.BatchNorm(data, name="g3_bn")
    aux_nodes = [n for n in bn._nodes() if n.op is None and n._is_aux]
    assert aux_nodes
    leaked = bn + Symbol([(aux_nodes[0], 0)])
    findings = lint_graph(leaked, check_consts=False)
    assert rules(findings) == {"GRF003"}
    assert lint_graph(bn, check_consts=False) == []


def test_grf004_float64_promotion():
    a = sym.var("a", dtype="float64")
    b = sym.var("b")
    findings = lint_graph(a * b, check_consts=False)
    assert rules(findings) == {"GRF004"}
    # all-f32 graph is silent
    assert lint_graph(sym.var("x") * sym.var("y"), check_consts=False) == []
    # explicit f64 Cast from f32 is flagged too
    assert rules(lint_graph(sym.Cast(sym.var("z"), dtype="float64"),
                            check_consts=False)) == {"GRF004"}


def test_grf005_static_reshape():
    data = sym.var("data")
    bad = sym.Reshape(data, shape=(32, 100), name="g5_bad")
    assert rules(lint_graph(bad, check_consts=False)) == {"GRF005"}
    ok = sym.Reshape(data, shape=(0, -1), name="g5_ok")
    assert lint_graph(ok, check_consts=False) == []


def test_grf005_node_level_suppression():
    data = sym.var("data")
    bad = sym.Reshape(data, shape=(32, 100), name="g5_muted")
    bad._set_attr(__mxlint_disable__="GRF005")
    assert lint_graph(bad, check_consts=False) == []


def test_grf006_large_baked_constant():
    big = np.ones((512, 600), np.float32)   # ~1.2 MiB
    ops_before = dict(registry._OPS)
    try:
        registry.register("_lintfix_bigconst",
                          doc="adds a >1MiB closure constant.")(
                              lambda data: data + jnp.asarray(big).sum())
        s = _sym_invoke(registry.get("_lintfix_bigconst"),
                        "_lintfix_bigconst", (sym.var("data"),), {})
        findings = lint_graph(s, shapes={"data": (4, 8)})
        assert rules(findings) == {"GRF006"}
        assert "MiB" in findings[0].message
    finally:
        registry._OPS.clear()
        registry._OPS.update(ops_before)


# ---------------------------------------------------------------------------
# source pass
# ---------------------------------------------------------------------------
def test_src001_scalar_capture():
    src = "loss = net.forward(batch)\nval = loss.item()\n"
    findings = lint_source(src, filename="train.py")
    assert rules(findings) == {"SRC001"}
    assert findings[0].subject == "train.py:2"
    # float() over an array expression is the same trap
    assert rules(lint_source("x = float(net(y))\n")) == {"SRC001"}


def test_src002_shape_branch():
    src = "if x.shape[0] > 16:\n    y = f(x)\nwhile x.size > 1:\n    x = g(x)\n"
    findings = lint_source(src)
    assert [f.rule_id for f in findings] == ["SRC002", "SRC002"]


def test_src_inline_suppression_and_clean():
    src = "v = loss.item()  # mxlint: disable=SRC001\n"
    assert lint_source(src) == []
    clean = "y = net(x)\nz = y + 1\n"
    assert lint_source(clean) == []


def test_src003_host_normalize_variants():
    """Host-side mean/std normalization is flagged with the fused
    device-tail suggestion (PR 3)."""
    # the spelled-out idiom
    assert rules(lint_source("x = (img - rgb_mean) / rgb_std\n")) == \
        {"SRC003"}
    # normalize helpers
    assert rules(lint_source("y = mx.image.color_normalize(img, m, s)\n")) \
        == {"SRC003"}
    assert rules(lint_source("aug = ColorNormalizeAug(mean, std)\n")) == \
        {"SRC003"}
    # iterator factories given mean/std without the device tail
    src = "it = mx.io.ImageRecordIter(path_imgrec=p, mean_r=123.0)\n"
    findings = lint_source(src)
    assert rules(findings) == {"SRC003"}
    assert "device_tail" in findings[0].message


def test_src003_clean_cases():
    # device_tail=True is exactly the fix — no finding
    ok = "it = ImageRecordIter(path_imgrec=p, mean_r=1.0, " \
         "device_tail=True)\n"
    assert lint_source(ok) == []
    # unrelated subtraction/division
    assert lint_source("z = (a - b) / c\n") == []
    # suppression works
    assert lint_source("x = (v - mean) / std  "
                       "# mxlint: disable=SRC003\n") == []


def test_src004_per_step_sync_in_training_loop():
    """A blocking host fetch at step frequency (same innermost loop as the
    dispatch) collapses the engine's run-ahead window — flagged."""
    src = ("for batch in it:\n"
           "    loss = trainer.step(batch.data, batch.label)\n"
           "    tot += float(loss.asscalar())\n")
    got = rules(lint_source(src))
    assert "SRC004" in got
    # np.asarray of a produced value in the step loop is the same trap
    src2 = ("for b in it:\n"
            "    mod.forward_backward(b)\n"
            "    mod.update()\n"
            "    hist.append(np.asarray(mod.get_outputs()[0]))\n")
    assert "SRC004" in rules(lint_source(src2))


def test_src004_clean_cases():
    # epoch-boundary fetch: the sync's innermost loop (epoch) does not
    # itself dispatch steps — the batch loop does
    epoch = ("for epoch in range(10):\n"
             "    tot = None\n"
             "    for b in it:\n"
             "        loss = trainer.step(b.data, b.label)\n"
             "        tot = loss if tot is None else tot + loss\n"
             "    print(float(tot.asscalar()))\n")
    assert "SRC004" not in rules(lint_source(epoch))
    # periodic flush guard (`if step % k == 0`) is the documented fix
    guarded = ("for step, b in enumerate(it):\n"
               "    loss = trainer.step(b.data, b.label)\n"
               "    if step % 50 == 0:\n"
               "        print(float(loss.asscalar()))\n")
    assert "SRC004" not in rules(lint_source(guarded))
    # a sync in a non-training loop (no step dispatch) is not SRC004
    evalloop = ("for b in it:\n"
                "    preds.append(net(b).asnumpy())\n")
    assert "SRC004" not in rules(lint_source(evalloop))
    # inline suppression
    sup = ("for b in it:\n"
           "    trainer.step(b.data, b.label)\n"
           "    v = loss.asscalar()  # mxlint: disable=SRC001,SRC004\n")
    assert rules(lint_source(sup)) == set()


def test_src004_shipped_loops_clean():
    """The --self-check sweep: every examples/ script and the in-repo fit
    loops are SRC004-clean (the loops this repo tells users to copy must
    not per-step sync)."""
    from mxnet_tpu.analysis import lint_shipped_loops
    assert lint_shipped_loops() == []


def test_doc001_rule_table_in_sync():
    """Every registered rule has a docs/analysis.md row (and the check is
    part of --self-check, so a new rule cannot land undocumented)."""
    from mxnet_tpu.analysis import lint_rule_docs
    assert lint_rule_docs() == []


# ---------------------------------------------------------------------------
# hooks: Symbol.lint / Module.lint / simple_bind(lint=True)
# ---------------------------------------------------------------------------
def _mlp():
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=8, name="lint_fc1")
    a = sym.Activation(h, act_type="relu", name="lint_relu")
    out = sym.FullyConnected(a, num_hidden=4, name="lint_fc2")
    return sym.SoftmaxOutput(out, name="lint_softmax")


def test_clean_graph_is_silent_end_to_end():
    net = _mlp()
    assert net.lint(shapes={"data": (2, 16)}) == []


def test_module_lint_uses_bound_shapes():
    mod = mx.module.Module(_mlp(), data_names=("data",),
                           label_names=("lint_softmax_label",))
    findings = mod.lint()          # unbound: shape-dependent rules skip
    assert findings == []
    mod.bind(data_shapes=[("data", (2, 16))],
             label_shapes=[("lint_softmax_label", (2,))])
    assert mod.lint() == []


def test_simple_bind_lint_raises_on_error():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="sb_fc")
    loss = sym.MakeLoss(sym.argmax(fc, axis=1, name="sb_argmax"),
                        name="sb_loss")
    with pytest.raises(MXNetError, match="GRF002"):
        loss.simple_bind(mx.cpu(), lint=True, data=(2, 8))
    # without lint the (broken) graph still binds as before
    ex = loss.simple_bind(mx.cpu(), data=(2, 8))
    assert ex is not None


def test_simple_bind_lint_warns_on_warning():
    data = sym.var("data")
    r = sym.Reshape(data, shape=(2, 16), name="sb_reshape")
    with pytest.warns(UserWarning, match="GRF005"):
        ex = r.simple_bind(mx.cpu(), lint=True, data=(2, 4, 4))
    assert ex.forward()[0].shape == (2, 16)


# ---------------------------------------------------------------------------
# reporters + CLI (satellite: CI tier-1 self-check)
# ---------------------------------------------------------------------------
def test_reporters_and_exit_codes():
    reg = FakeReg()
    reg.add(registry.Op("nodoc", lambda data: data))
    findings = lint_registry(registry=reg)
    text = render_text(findings)
    assert "REG009" in text and "nodoc" in text
    payload = json.loads(render_json(findings))
    assert payload["version"] == 1
    assert payload["findings"][0]["rule"] == "REG009"
    assert payload["counts"] == {"warning": 1}
    assert exit_code(findings, strict=False) == 0
    assert exit_code(findings, strict=True) == 1
    assert exit_code([], strict=True) == 0


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-m", "mxnet_tpu.analysis"]
                          + list(args), capture_output=True, text=True,
                          cwd=REPO, env=env, timeout=300)


def test_self_check_cli_clean_on_shipped_registry():
    """CI gate: new op registrations that break a registry invariant (or
    land without docs/coverage) fail here before anything executes."""
    proc = _run_cli("--self-check")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_source_lint_json(tmp_path):
    script = tmp_path / "bad_train.py"
    script.write_text("for b in loader:\n"
                      "    v = model(b).item()\n"
                      "    if b.shape[0] < 8:\n"
                      "        break\n")
    proc = _run_cli(str(script), "--json", "--strict")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    got = {f["rule"] for f in payload["findings"]}
    assert got == {"SRC001", "SRC002"}


# ---------------------------------------------------------------------------
# cost pass (mxcost): golden per-op models, liveness, transfer,
# collectives, XLA cross-validation, determinism
# ---------------------------------------------------------------------------
import jax
from jax import lax

from mxnet_tpu.analysis import cost as mxcost


def _xla_flops(fn, *args):
    c = jax.jit(fn).lower(*args).compile().cost_analysis()
    d = c[0] if isinstance(c, list) else c
    return float(d.get("flops", 0.0)), float(d.get("transcendentals", 0.0))


def test_cost_dot_general_golden():
    r = mxcost.analyze_fn(lambda a, b: a @ b,
                          jnp.zeros((64, 128)), jnp.zeros((128, 256)))
    assert r.flops == 2 * 64 * 128 * 256
    assert r.per_primitive["dot_general"]["count"] == 1
    # batched matmul counts the batch dims too
    rb = mxcost.analyze_fn(jnp.matmul, jnp.zeros((4, 8, 16)),
                           jnp.zeros((4, 16, 32)))
    assert rb.flops == 2 * 4 * 8 * 16 * 32


def test_cost_conv_golden():
    x = jnp.zeros((8, 32, 32, 16))
    w = jnp.zeros((3, 3, 16, 32))

    def conv(a, b):
        return lax.conv_general_dilated(
            a, b, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))

    r = mxcost.analyze_fn(conv, x, w)
    assert r.flops == 2 * 8 * 32 * 32 * 32 * 9 * 16


def test_cost_reduce_golden():
    r = mxcost.analyze_fn(lambda x: x.sum(axis=1), jnp.zeros((64, 1000)))
    assert r.flops == 64 * 1000 - 64
    rmax = mxcost.analyze_fn(lambda x: x.max(), jnp.zeros((128,)))
    assert rmax.flops == 127


def test_cost_elementwise_and_transcendental():
    r = mxcost.analyze_fn(lambda x: x + x, jnp.zeros((64, 1000)))
    assert r.flops == 64000 and r.transcendentals == 0
    re_ = mxcost.analyze_fn(jnp.exp, jnp.zeros((64, 1000)))
    assert re_.flops == 0 and re_.transcendentals == 64000


def test_cost_reshape_and_movement_are_free():
    r = mxcost.analyze_fn(lambda x: x.reshape(-1).T, jnp.zeros((16, 32)))
    assert r.flops == 0 and r.transcendentals == 0
    # but the bytes moved are counted
    assert r.bytes_read >= 16 * 32 * 4


def test_cost_collective_bytes_per_axis():
    n = 1 << 20
    r = mxcost.analyze_fn(lambda x: lax.psum(x, "data"),
                          jnp.zeros((n,), jnp.float32),
                          axis_env=[("data", 8)])
    # ring all-reduce: 2*(K-1)/K * payload
    assert r.collective_bytes_per_axis == {
        "data": int(2 * 7 * (n * 4) // 8)}
    # all_gather moves the OUTPUT around the ring: (K-1)/K x (K x input)
    rg = mxcost.analyze_fn(lambda x: lax.all_gather(x, "data"),
                           jnp.zeros((n,), jnp.float32),
                           axis_env=[("data", 8)])
    assert rg.collective_bytes_per_axis == {"data": int(7 * (n * 4))}
    # reduce_scatter moves the input: (K-1)/K x input
    rs = mxcost.analyze_fn(
        lambda x: lax.psum_scatter(x, "data", scatter_dimension=0,
                                   tiled=True),
        jnp.zeros((n,), jnp.float32), axis_env=[("data", 8)])
    assert rs.collective_bytes_per_axis == {"data": int(7 * (n * 4) // 8)}
    # grouped psum: ONE ring over the combined group (K = 8 x 4),
    # attributed per axis proportionally to (size - 1); the per-axis
    # sum equals the group total exactly
    gp = mxcost.analyze_fn(lambda x: lax.psum(x, ("data", "model")),
                           jnp.zeros((n,), jnp.float32),
                           axis_env=[("data", 8), ("model", 4)])
    total = int(2 * 31 * (n * 4) // 32)
    assert sum(gp.collective_bytes_per_axis.values()) == total
    assert set(gp.collective_bytes_per_axis) == {"data", "model"}
    assert gp.collective_bytes_per_axis["data"] == total - total * 3 // 10
    # ppermute prices one hop of the payload
    pp = mxcost.analyze_fn(
        lambda x: lax.ppermute(x, "data",
                               [(i, (i + 1) % 8) for i in range(8)]),
        jnp.zeros((n,), jnp.float32), axis_env=[("data", 8)])
    assert pp.collective_bytes_per_axis == {"data": n * 4}
    # axis of size 1 moves nothing
    r1 = mxcost.analyze_fn(lambda x: lax.psum(x, "data"),
                           jnp.zeros((n,)), axis_env=[("data", 1)])
    assert r1.collective_bytes == 0


def test_cost_transfer_classification():
    w = jnp.zeros((256, 256))
    x = jnp.zeros((8, 256))
    r = mxcost.analyze_fn(lambda w, x: x @ w, w, x, host_argnums=(1,))
    # only x is host-fed; the output (8,256) f32 is fetched
    assert r.transfer_h2d_bytes == 8 * 256 * 4
    assert r.transfer_d2h_bytes == 8 * 256 * 4
    assert r.input_bytes == (256 * 256 + 8 * 256) * 4


def test_cost_peak_hbm_liveness_and_donation():
    # chain: big intermediate dies after use; peak = inputs + biggest
    # simultaneous pair
    def f(x):
        a = x * 2.0        # 4 MiB live with x
        b = a.sum(axis=1)  # a dies after this
        return b

    x = jnp.zeros((1024, 1024))
    nb = 1024 * 1024 * 4
    r = mxcost.analyze_fn(f, x)
    # non-donated input resident + intermediate a + the (1024,) output
    assert r.peak_hbm_bytes == nb + nb + 1024 * 4
    # donating x does not change the peak here (x is live when a is
    # written) but a donated input must not outlive its last use:
    def g(x):
        a = x * 2.0
        b = a * 3.0        # x already dead if donated
        return b.sum()

    rd = mxcost.analyze_fn(g, x, donate_argnums=(0,))
    rn = mxcost.analyze_fn(g, x)
    assert rd.peak_hbm_bytes < rn.peak_hbm_bytes


def test_cost_nested_jit_is_inlined():
    inner = jax.jit(lambda a, b: a @ b)
    r = mxcost.analyze_fn(lambda a, b: inner(a, b) + 1.0,
                          jnp.zeros((32, 32)), jnp.zeros((32, 32)))
    assert r.per_primitive["dot_general"]["flops"] == 2 * 32 * 32 * 32


def test_cost_xla_cross_validation():
    """Modeled flops vs XLA's own post-compile cost_analysis() on CPU,
    within the documented XLA_FLOP_RTOL for the golden ops."""
    x = jnp.zeros((8, 32, 32, 16))
    w = jnp.zeros((3, 3, 16, 32))
    cases = [
        ("dot", lambda a, b: a @ b,
         (jnp.zeros((64, 128)), jnp.zeros((128, 256)))),
        ("conv", lambda a, b: lax.conv_general_dilated(
            a, b, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")), (x, w)),
        ("reduce", lambda a: a.sum(axis=1), (jnp.zeros((64, 1000)),)),
        ("add", lambda a: a + a, (jnp.zeros((64, 1000)),)),
        ("exp", jnp.exp, (jnp.zeros((64, 1000)),)),
    ]
    for name, fn, args in cases:
        modeled = mxcost.analyze_fn(fn, *args)
        xla_f, xla_t = _xla_flops(fn, *args)
        if xla_f:
            err = abs(modeled.flops - xla_f) / xla_f
            assert err <= mxcost.XLA_FLOP_RTOL, (name, modeled.flops,
                                                 xla_f, err)
        if xla_t:
            err = abs(modeled.transcendentals - xla_t) / xla_t
            assert err <= mxcost.XLA_FLOP_RTOL, (name, err)


def test_cost_determinism_and_self_check():
    from mxnet_tpu.analysis import cost_self_check
    a = mxcost.analyze_fn(lambda x: jnp.exp(x @ x.T).sum(),
                          jnp.zeros((32, 32))).as_dict()
    b = mxcost.analyze_fn(lambda x: jnp.exp(x @ x.T).sum(),
                          jnp.zeros((32, 32))).as_dict()
    assert a == b
    assert cost_self_check() == []


def test_cost_report_dict_shape():
    r = mxcost.analyze_fn(lambda a, b: a @ b, jnp.zeros((4, 8)),
                          jnp.zeros((8, 2)))
    d = r.as_dict()
    for key in ("flops", "transcendentals", "bytes_read", "bytes_written",
                "transfer_bytes", "collective_bytes_per_axis",
                "peak_hbm_bytes", "per_primitive", "n_eqns"):
        assert key in d
    assert "mxcost" in r.render()


# ---------------------------------------------------------------------------
# DST distributed-step rules
# ---------------------------------------------------------------------------
from mxnet_tpu.analysis import dist_lint


def _step_jaxpr(fn, *avals, axis=8):
    return jax.make_jaxpr(fn, axis_env=[("data", axis)])(*avals)


def test_dst001_missing_grad_reduction():
    """A step that applies raw per-replica grads leaves the new weights
    replica-varying."""
    w = jnp.zeros((16, 4))
    x = jnp.zeros((8, 16))

    def bad_step(w, x):
        g = jax.grad(lambda w: (x @ w).sum())(w)
        return w - 0.1 * g          # no pmean: replicas diverge

    closed = _step_jaxpr(bad_step, w, x)
    findings = dist_lint.lint_dist_step(
        closed, "data", varying_invars=[1], param_outvars=[0],
        param_names=["w"], axis_size=8)
    assert rules(findings) == {"DST001"}
    assert findings[0].subject == "w"

    def good_step(w, x):
        g = jax.grad(lambda w: (x @ w).sum())(w)
        return w - 0.1 * lax.pmean(g, "data")

    closed = _step_jaxpr(good_step, w, x)
    assert dist_lint.lint_dist_step(
        closed, "data", varying_invars=[1], param_outvars=[0],
        param_names=["w"], axis_size=8) == []


def test_dst002_duplicate_reduction():
    def dup_step(w, x):
        g = jax.grad(lambda w: (x @ w).sum())(w)
        g = lax.psum(g, "data")
        return w - lax.psum(g, "data")   # second psum: scales by K

    closed = _step_jaxpr(dup_step, jnp.zeros((16, 4)), jnp.zeros((8, 16)))
    findings = dist_lint.lint_dist_step(
        closed, "data", varying_invars=[1], param_outvars=[0],
        param_names=["w"], axis_size=8)
    assert rules(findings) == {"DST002"}


def test_dst004_subf32_collective_is_error():
    """Tightened DST004 (docs/precision.md): reducing bf16 over the
    data axis is an ERROR — cast-to-f32-then-reduce is the CORRECT
    mixed-precision spelling and traces clean."""
    # the broken spelling: bf16 on the wire
    closed = _step_jaxpr(lambda g: lax.psum(g, "data"),
                         jnp.zeros((1024,), jnp.bfloat16))
    findings = dist_lint.lint_dist_step(
        closed, "data", varying_invars=[0], param_outvars=[],
        axis_size=8)
    assert rules(findings) == {"DST004"}
    assert findings[0].severity == "error"
    assert "bfloat16" in findings[0].message

    # reduce-in-bf16-widen-after is the SAME wire bug
    closed_rs = _step_jaxpr(
        lambda g: lax.psum_scatter(g, "data", scatter_dimension=0,
                                   tiled=True).astype(jnp.float32),
        jnp.zeros((1024,), jnp.bfloat16))
    findings_rs = dist_lint.lint_dist_step(
        closed_rs, "data", varying_invars=[0], param_outvars=[],
        axis_size=8)
    assert "DST004" in rules(findings_rs)
    assert any(f.severity == "error" for f in findings_rs
               if f.rule_id == "DST004")

    # the correct spelling: widen BEFORE the collective — clean
    closed2 = _step_jaxpr(lambda g: lax.psum(g.astype(jnp.float32),
                                             "data"),
                          jnp.zeros((1024,), jnp.bfloat16))
    assert dist_lint.lint_dist_step(
        closed2, "data", varying_invars=[0], param_outvars=[],
        axis_size=8) == []

    # the retained widen flavor: an ALREADY-f32 operand widened to f64
    # right before the wire stays a warning (x64 scoped: jax silently
    # maps float64 to float32 otherwise)
    from jax.experimental import enable_x64
    with enable_x64():
        closed3 = _step_jaxpr(lambda g: lax.psum(g.astype(jnp.float64),
                                                 "data"),
                              jnp.zeros((1024,), jnp.float32))
    findings3 = dist_lint.lint_dist_step(
        closed3, "data", varying_invars=[0], param_outvars=[],
        axis_size=8)
    assert rules(findings3) == {"DST004"}
    assert findings3[0].severity == "warning"
    assert "float32->float64" in findings3[0].message


def test_dst005_baked_step_constant():
    lr = np.float32(0.1)        # python-side value baked into the trace

    def step(w, x):
        g = lax.pmean(jax.grad(lambda w: (x @ w).sum())(w), "data")
        return w - jnp.asarray(np.full((16, 4), lr)) * g

    closed = _step_jaxpr(step, jnp.zeros((16, 4)), jnp.zeros((8, 16)))
    assert closed.consts, "fixture should bake a constant"
    findings = dist_lint.lint_dist_step(
        closed, "data", varying_invars=[1], param_outvars=[0],
        param_names=["w"], axis_size=8)
    assert rules(findings) == {"DST005"}


def _make_trainer(**kwargs):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx.init.Xavier())
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, **kwargs)


def test_trainer_lint_clean():
    tr = _make_trainer()
    assert tr.lint(data_shape=(64, 16), label_shape=(64,)) == []
    # and the cost report of the same step is populated
    rep = tr.cost_report(data_shape=(64, 16), label_shape=(64,))
    assert rep.flops > 0 and rep.collective_bytes > 0
    assert rep.transfer_h2d_bytes == 64 * 16 * 4 + 64 * 4


def test_trainer_lint_catches_removed_grad_psum(monkeypatch):
    """The acceptance bug class: the gradient reduction deleted from
    DataParallelTrainer — every trainable param raises DST001."""
    from mxnet_tpu.parallel import DataParallelTrainer
    monkeypatch.setattr(DataParallelTrainer, "_reduce_grads",
                        lambda self, grads: grads)
    tr = _make_trainer()
    findings = tr.lint(data_shape=(64, 16), label_shape=(64,))
    assert "DST001" in rules(findings)
    subjects = {f.subject for f in findings if f.rule_id == "DST001"}
    # all four MLP params (2x weight, 2x bias) desync, and the loss is
    # no longer the global mean either
    assert len(subjects) >= 4


def test_dst003_param_sharded_over_data_axis():
    from jax.sharding import PartitionSpec
    # shard only the 8-divisible params over the data axis so setup's
    # device_put succeeds and the *lint* is what reports the bug
    tr = _make_trainer(param_spec_fn=lambda name, shape:
                       PartitionSpec("data")
                       if int(shape[0]) % 8 == 0 else PartitionSpec())
    findings = tr.lint(data_shape=(64, 16), label_shape=(64,))
    assert "DST003" in rules(findings)
    msgs = " ".join(f.message for f in findings
                    if f.rule_id == "DST003")
    assert "data" in msgs


def test_dst003_batch_not_divisible():
    tr = _make_trainer()
    findings = tr.lint(data_shape=(30, 16), label_shape=(30,),
                       declared_axis_size=8)
    assert any(f.rule_id == "DST003" and f.subject == "data"
               for f in findings)


# ---------------------------------------------------------------------------
# budget gate: STATIC_BUDGETS.json + tools/update_budgets.py
# ---------------------------------------------------------------------------
def test_budget_gate_cli():
    """CI gate: the checked-in budgets pass on the seed models."""
    proc = _run_cli("--cost", "--budget",
                    os.path.join(REPO, "STATIC_BUDGETS.json"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_budget_gate_fails_on_flop_regression(tmp_path):
    """A budget whose flops entry is >10% below the modeled value is
    exactly what a flop-doubling PR produces: COST001, exit 2."""
    with open(os.path.join(REPO, "STATIC_BUDGETS.json")) as f:
        budget = json.load(f)
    budget["models"]["mlp_train_step"]["flops"] = int(
        budget["models"]["mlp_train_step"]["flops"] / 1.5)
    bad = tmp_path / "budgets.json"
    bad.write_text(json.dumps(budget))
    proc = _run_cli("--cost", "--budget", str(bad))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "COST001" in proc.stdout

    # and a stale (too-high) budget is a COST002 warning: rc 0 plain,
    # rc 1 under --strict
    budget["models"]["mlp_train_step"]["flops"] = int(
        budget["models"]["mlp_train_step"]["flops"] * 4)
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps(budget))
    proc = _run_cli("--cost", "--budget", str(stale))
    assert proc.returncode == 0 and "COST002" in proc.stdout
    proc = _run_cli("--cost", "--budget", str(stale), "--strict")
    assert proc.returncode == 1


def test_budget_gate_unknown_model(tmp_path):
    bad = tmp_path / "budgets.json"
    bad.write_text(json.dumps({
        "tolerance_pct": 10,
        "models": {"no_such_model": {"flops": 1}}}))
    proc = _run_cli("--cost", "--budget", str(bad))
    assert proc.returncode == 2
    assert "COST001" in proc.stdout and "no_such_model" in proc.stdout


def test_update_budgets_check_mode(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    tool = os.path.join(REPO, "tools", "update_budgets.py")
    proc = subprocess.run(
        [sys.executable, tool, "--check"], capture_output=True,
        text=True, cwd=REPO, env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # regenerating into a scratch path writes a loadable, gate-clean file
    out = tmp_path / "budgets.json"
    proc = subprocess.run(
        [sys.executable, tool, "--path", str(out)], capture_output=True,
        text=True, cwd=REPO, env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    written = json.loads(out.read_text())
    assert written["models"] and written["tolerance_pct"] == 10.0
    proc = subprocess.run(
        [sys.executable, tool, "--check", "--path", str(out)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cost_json_schema_version():
    proc = _run_cli("--cost", "--json", "--model", "mlp_infer")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema_version"] == 6    # 6: the codegen section
    assert payload["version"] == 1
    assert "mlp_infer" in payload["cost"]
    assert payload["cost"]["mlp_infer"]["flops"] > 0
    assert payload["dist"]["rules"][0] == "DST001"


# ---------------------------------------------------------------------------
# cost hooks: Symbol / Module / serving ModelRunner
# ---------------------------------------------------------------------------
def test_symbol_and_module_cost_report():
    net = _mlp()
    rep = net.cost_report(shapes={"data": (2, 16)})
    assert rep is not None and rep.flops > 0
    # FC1 dominates: 2*2*16*8 + FC2 2*2*8*4
    assert rep.per_primitive["dot_general"]["flops"] == \
        2 * 2 * 16 * 8 + 2 * 2 * 8 * 4
    # host-fed = the names shapes were given for
    assert rep.transfer_h2d_bytes == 2 * 16 * 4

    mod = mx.module.Module(_mlp(), data_names=("data",),
                           label_names=("lint_softmax_label",))
    assert mod.cost_report() is None          # unbound: no shapes
    mod.bind(data_shapes=[("data", (2, 16))],
             label_shapes=[("lint_softmax_label", (2,))])
    mrep = mod.cost_report()
    assert mrep is not None and mrep.flops == rep.flops


def test_serving_modeled_cost_and_srv003():
    import mxnet_tpu.serving as serving
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=16, name="srv_fc1")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Activation(h, act_type="relu"),
                           num_hidden=3, name="srv_fc2"),
        name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    runner = serving.ModelRunner(mod, buckets=(1, 4), example_shape=(8,))
    cost = runner.modeled_cost()
    assert set(cost) == {1, 4}
    for b, row in cost.items():
        assert row["flops"] > 0 and row["peak_hbm_bytes"] > 0
    # flops scale with the bucket's batch
    assert cost[4]["flops"] > cost[1]["flops"]
    # SRV003: a cap below the modeled HBM flags at load
    with pytest.warns(UserWarning, match="SRV003"):
        serving.ModelRunner(mod, buckets=(1, 4), example_shape=(8,),
                            hbm_cap_bytes=16, warmup=False)
    # a generous cap stays silent (no SRV003 in any warning)
    import warnings as _warnings
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        serving.ModelRunner(mod, buckets=(1, 4), example_shape=(8,),
                            hbm_cap_bytes=1 << 30, warmup=False)
    assert not any("SRV003" in str(w.message) for w in caught)


def test_srv004_fleet_hbm_packing():
    from mxnet_tpu.analysis import lint_fleet_hbm
    # under cap / no cap: clean
    assert lint_fleet_hbm({"a": 600 << 20, "b": 300 << 20}, 1 << 30) == []
    assert lint_fleet_hbm({"a": 600 << 20, "b": 600 << 20}, 0) == []
    # over cap: one SRV004 error carrying the per-model modeled numbers
    found = lint_fleet_hbm({"a": 600 << 20, "b": 500 << 20, "c": None},
                           1 << 30)
    assert [f.rule_id for f in found] == ["SRV004"]
    assert found[0].severity == "error"
    msg = found[0].message
    assert "a=600.0 MiB" in msg and "b=500.0 MiB" in msg
    assert "1100.0 MiB" in msg and "1024.0 MiB" in msg
    assert "c" in msg        # unmodelable runners are named, not counted


def test_srv004_deadline_propagation():
    from mxnet_tpu.analysis import lint_deadline_propagation
    bad = (
        "def handler(payload):\n"
        "    deadline_ms = payload.get('deadline_ms')\n"
        "    return fleet.submit(payload['x'], tier='gold')\n")
    found = lint_deadline_propagation(source=bad)
    assert [f.rule_id for f in found] == ["SRV004"]
    assert "handler" in found[0].message
    # propagating the kwarg (or an opaque **kwargs splat) is clean, and
    # functions that never bind deadline_ms are out of scope
    good = bad.replace("tier='gold'", "tier='gold', deadline_ms=deadline_ms")
    splat = bad.replace("tier='gold'", "**kw")
    unbound = "def f(x):\n    return fleet.submit(x)\n"
    infer_bad = bad.replace(".submit", ".infer")
    assert lint_deadline_propagation(source=good) == []
    assert lint_deadline_propagation(source=splat) == []
    assert lint_deadline_propagation(source=unbound) == []
    assert [f.rule_id for f in lint_deadline_propagation(
        source=infer_bad)] == ["SRV004"]


def test_srv004_shipped_serving_sources_clean():
    """The --self-check sweep: every shipped serving request path
    (mxnet_tpu/serving/, tools/serve.py, examples/serving/) propagates
    deadline_ms to its submit/infer sinks."""
    from mxnet_tpu.analysis import lint_serving_sources
    assert lint_serving_sources() == []


def test_srv004_fleet_registration_refused_end_to_end():
    """ModelFleet.register is the enforcement point: the refusal error
    carries the rendered SRV004 finding."""
    import mxnet_tpu.serving as serving
    data = sym.var("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=3, name="sf4_fc"),
        name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (2, 8))],
             label_shapes=[("softmax_label", (2,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    runner = serving.ModelRunner(mod, buckets=(1, 2), example_shape=(8,))
    hbm = runner.modeled_peak_hbm()
    assert hbm and hbm > 0
    fleet = serving.ModelFleet(hbm_cap_bytes=hbm)      # exactly one fits
    fleet.register("one", runner)
    with pytest.raises(MXNetError, match="SRV004"):
        fleet.register("two", runner, hbm_bytes=1)
    fleet.drain()


def test_srv005_wallclock_reads_flagged_and_suppressed():
    """SRV005: wall-clock calls in promotion/capacity decision code are
    errors; an inline justified disable (the measurement escape hatch)
    and non-clock receivers are clean."""
    from mxnet_tpu.analysis import lint_wallclock_reads
    bad = (
        "import time, datetime\n"
        "def decide(metrics):\n"
        "    t0 = time.monotonic()\n"
        "    stamp = datetime.datetime.now()\n"
        "    time.sleep(1.0)\n"
        "    return t0, stamp\n")
    found = lint_wallclock_reads(source=bad)
    assert [f.rule_id for f in found] == ["SRV005"] * 3
    assert all(f.severity == "error" for f in found)
    assert "time.monotonic" in found[0].message
    # the justified-measurement escape hatch: inline disable per line
    ok = bad.replace(
        "time.monotonic()",
        "time.monotonic()  # mxlint: disable=SRV005 - measuring")
    assert len(lint_wallclock_reads(source=ok)) == 2
    # an arbitrary object's .now()/.sleep() is not a clock read
    clean = ("def decide(sched):\n"
             "    return sched.now() + queue.sleep(3)\n")
    assert lint_wallclock_reads(source=clean) == []


def test_srv005_shipped_mlops_sources_clean():
    """The --self-check sweep: mxnet_tpu/mlops/ plus the decision CLIs
    (tools/promote.py, tools/capacity.py) carry no unjustified
    wall-clock reads — promotion reruns stay byte-identical."""
    from mxnet_tpu.analysis import lint_promotion_sources
    assert lint_promotion_sources() == []


def test_srv005_sweep_catches_injected_clock(tmp_path):
    """End-to-end through the sweep plumbing: a wall-clock read written
    into a fake mlops/ tree is found by the same path --self-check
    runs."""
    from mxnet_tpu.analysis.mlops_lint import lint_promotion_sources
    root = tmp_path / "mxnet_tpu"
    (root / "mlops").mkdir(parents=True)
    (root / "mlops" / "promote.py").write_text(
        "import time\n"
        "def evaluate():\n"
        "    if time.time() % 60 < 30:\n"
        "        return 'promote'\n")
    found = lint_promotion_sources(root=str(root))
    assert [f.rule_id for f in found] == ["SRV005"]
    assert "promote.py:3" in found[0].subject


def test_serving_stats_expose_modeled_cost():
    from mxnet_tpu.serving.stats import ServingStats  # noqa: F401  (sanity)
    import mxnet_tpu.serving as serving
    data = sym.var("data")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=3, name="ss_fc"),
        name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (2, 8))],
             label_shapes=[("softmax_label", (2,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    runner = serving.ModelRunner(mod, buckets=(1, 2), example_shape=(8,))
    server = serving.Server(runner, port=0)
    host, port = server.start()
    try:
        import http.client
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("GET", "/stats")
        resp = json.loads(conn.getresponse().read())
        assert set(resp["modeled_cost"]) == {"1", "2"}
        assert resp["modeled_cost"]["2"]["flops"] > 0
    finally:
        server.drain(timeout=10)


# ---------------------------------------------------------------------------
# TEL001: chaos probe sites vs the registered fault model (ISSUE 9)
# ---------------------------------------------------------------------------
def test_tel001_shipped_sites_clean():
    """Every probe site used in the shipped sources is registered in
    chaos.SITES, every registered site is probed somewhere, the docs
    table covers them all, and maybe_inject still stamps fired faults
    through telemetry.fault_event."""
    from mxnet_tpu.analysis import lint_chaos_sites
    assert lint_chaos_sites() == []


def test_tel001_detects_drift(tmp_path):
    """A probe site used-but-unregistered, a registered-but-unused
    fault model entry, and a non-literal site name all fire TEL001."""
    from mxnet_tpu.analysis import lint_chaos_sites
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "from resilience import chaos\n"
        "def f(name):\n"
        "    chaos.maybe_inject('totally.unregistered')\n"
        "    chaos.maybe_inject(name)\n")
    findings = lint_chaos_sites(root=str(pkg))
    subjects = {f.subject for f in findings}
    rules = {f.rule_id for f in findings}
    assert rules == {"TEL001"}
    # used but unregistered
    assert "totally.unregistered" in subjects
    # non-literal site argument
    assert any(s.endswith("mod.py:4") for s in subjects)
    # every registered site is "unused" under this synthetic root
    from mxnet_tpu.resilience.chaos import SITES
    assert set(SITES) <= subjects
    # the synthetic root has no chaos.py: the emission check fires too
    assert "chaos.maybe_inject" in subjects


def test_tel001_probe_site_scan_matches_fault_model():
    """probe_sites_used finds every shipped maybe_inject literal —
    including the drivers outside the package (bench.py backend.init)."""
    from mxnet_tpu.analysis import probe_sites_used
    from mxnet_tpu.resilience.chaos import SITES
    used, dynamic = probe_sites_used()
    assert not dynamic
    assert set(used) == set(SITES)
    assert any(w.startswith("bench.py:") for w in used["backend.init"])


# ---------------------------------------------------------------------------
# TEL002: attribution phase names vs docs table vs doctor hint map (ISSUE 10)
# ---------------------------------------------------------------------------
def test_tel002_shipped_phases_clean():
    """Every add_phase literal in the shipped sources is declared in
    attribution.PHASES, every declared phase is measured somewhere, the
    HINTS map and the docs/observability.md phase table cover exactly
    that set — both ways."""
    from mxnet_tpu.analysis import lint_attribution_phases
    assert lint_attribution_phases() == []


def test_tel002_phase_scan_matches_declaration():
    """attribution_phases_used finds every shipped add_phase literal;
    the declared PHASES/HINTS parse out of attribution.py by AST."""
    from mxnet_tpu.analysis import (attribution_phase_decls,
                                    attribution_phases_used)
    from mxnet_tpu.telemetry.attribution import HINTS, PHASES
    phases, hints = attribution_phase_decls()
    assert phases == list(PHASES)
    assert set(hints) == set(HINTS)
    used, dynamic = attribution_phases_used()
    assert not dynamic
    assert set(used) == set(PHASES)
    # the trainer is the instrumentation spine: every phase has at least
    # one call site in parallel/trainer.py
    for phase in PHASES:
        assert any("trainer.py" in w for w in used[phase]), (phase, used)


def test_tel002_detects_drift(tmp_path):
    """An undeclared phase measured in code, a declared-but-unmeasured
    phase, a HINTS/PHASES mismatch, a docs-table mismatch and a
    non-literal phase name all fire TEL002 (error)."""
    from mxnet_tpu.analysis import lint_attribution_phases
    from mxnet_tpu.analysis.findings import RULES, ERROR
    assert RULES["TEL002"][0] == ERROR
    pkg = tmp_path / "pkg"
    (pkg / "telemetry").mkdir(parents=True)
    (pkg / "telemetry" / "attribution.py").write_text(
        "PHASES = ('never_measured', 'documented_less')\n"
        "HINTS = {'never_measured': 'hint', 'ghost_phase': 'stale'}\n")
    (pkg / "mod.py").write_text(
        "def f(attr, name):\n"
        "    attr.add_phase('undeclared_phase', 0.1)\n"
        "    attr.add_phase(name, 0.2)\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "| phase | measured where | doctor hint names |\n"
        "|---|---|---|\n"
        "| `never_measured` | somewhere | knob |\n"
        "| `only_in_docs` | nowhere | knob |\n")
    findings = lint_attribution_phases(root=str(pkg))
    assert {f.rule_id for f in findings} == {"TEL002"}
    subjects = {f.subject for f in findings}
    assert "undeclared_phase" in subjects       # measured, not declared
    assert "documented_less" in subjects        # declared, never measured
    assert "ghost_phase" in subjects            # stale HINTS key
    assert "only_in_docs" in subjects           # docs row with no phase
    assert any(s.endswith("mod.py:3") for s in subjects)  # non-literal
    # a PHASES tuple that is no longer a literal is itself a finding
    (pkg / "telemetry" / "attribution.py").write_text(
        "PHASES = tuple(x for x in ['a'])\n")
    findings = lint_attribution_phases(root=str(pkg))
    assert any(f.subject == "PHASES" for f in findings)


def test_tel002_in_self_check(tmp_path):
    """TEL002 drift fails `--self-check` end to end: tamper with the
    phase table in a copied docs file and sweep against it."""
    from mxnet_tpu.analysis import lint_attribution_phases
    import mxnet_tpu.analysis.telemetry_lint as tl
    import os
    doc = tmp_path / "observability.md"
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(tl.__file__))), os.pardir, "docs",
            "observability.md")) as f:
        text = f.read()
    doc.write_text(text.replace("| `input_wait` |", "| `renamed_wait` |"))
    findings = lint_attribution_phases(doc_path=str(doc))
    subjects = {f.subject for f in findings}
    assert "input_wait" in subjects      # phase lost its docs row
    assert "renamed_wait" in subjects    # docs row without a phase
