"""mxnet_tpu.analysis (mxlint) — registry, graph and source passes.

Every rule_id fires at least once on a crafted fixture and stays silent
on a clean op/graph; the self-check CLI (what CI runs) passes on the
shipped registry.
"""
import functools
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym

pytestmark = pytest.mark.analysis
from mxnet_tpu.analysis import (lint_graph, lint_registry, lint_source,
                                render_json, render_text, exit_code)
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ops import registry
from mxnet_tpu.symbol.symbol import Symbol, _sym_invoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules(findings):
    return {f.rule_id for f in findings}


# ---------------------------------------------------------------------------
# registry pass — against an isolated fake registry (the real one must stay
# clean, which test_self_check_cli proves)
# ---------------------------------------------------------------------------
class FakeReg:
    def __init__(self):
        self._ops = {}
        self._shadows = []

    def add(self, op, *names):
        for n in (op.name,) + names:
            self._ops[n] = op
        return op

    def list_ops(self):
        return sorted(self._ops)

    def get(self, name):
        return self._ops[name]

    def shadowed(self):
        return list(self._shadows)


def _good_fn(data, weight, alpha=1.0):
    """A well-formed fixture op."""
    return data * weight * alpha


def test_clean_op_is_silent():
    reg = FakeReg()
    reg.add(registry.Op("good", _good_fn, arg_names=["data", "weight"],
                        scalar_args=("alpha",)))
    assert lint_registry(registry=reg) == []


def test_reg001_missing_tensor_slot():
    reg = FakeReg()
    reg.add(registry.Op("bad", lambda data: data,
                        arg_names=["data", "weight"],
                        doc="fn has one positional param, two slots."))
    assert rules(lint_registry(registry=reg)) == {"REG001"}


def test_reg001_variadic_without_star_args():
    reg = FakeReg()
    reg.add(registry.Op("badvar", lambda data: data, arg_names=["args"],
                        doc="variadic registration over a unary fn."))
    assert "REG001" in rules(lint_registry(registry=reg))


def test_reg002_slot_order_swap():
    reg = FakeReg()
    reg.add(registry.Op("swapped", lambda weight, data: data @ weight,
                        arg_names=["data", "weight"],
                        doc="slots transposed vs fn params."))
    assert rules(lint_registry(registry=reg)) == {"REG002"}


def test_reg003_unknown_scalar_arg():
    reg = FakeReg()
    reg.add(registry.Op("badscalar", lambda data: data,
                        scalar_args=("alpha",),
                        doc="alpha is not a parameter of fn."))
    assert rules(lint_registry(registry=reg)) == {"REG003"}


def test_reg004_unknown_optional_arg():
    reg = FakeReg()
    reg.add(registry.Op("badopt", lambda data, bias=None: data,
                        arg_names=["data", "bias"],
                        optional_args=("nonexistent",),
                        doc="optional names no slot."))
    assert rules(lint_registry(registry=reg)) == {"REG004"}


def test_reg005_aux_index_gap():
    reg = FakeReg()
    reg.add(registry.Op("badaux",
                        lambda data, gamma, mean=None, var=None: data,
                        arg_names=["data", "gamma"],
                        aux={3: "mean", 4: "var"},   # should start at 2
                        doc="aux range leaves a hole at index 2."))
    assert rules(lint_registry(registry=reg)) == {"REG005"}


def test_reg006_mutates_out_of_range():
    reg = FakeReg()
    reg.add(registry.Op("badmut", lambda w, g: (w, w - g),
                        arg_names=["weight", "grad"], mutates={5: 1},
                        doc="mutated input index 5 does not exist."))
    assert rules(lint_registry(registry=reg)) == {"REG006"}


def test_reg007_num_outputs_not_total():
    reg = FakeReg()
    reg.add(registry.Op("badnout", lambda data: data,
                        num_outputs=lambda p: p["k"],   # KeyError on {}
                        doc="num_outputs requires an undefaulted param."))
    assert rules(lint_registry(registry=reg)) == {"REG007"}


def test_reg008_alias_shadow():
    reg = FakeReg()
    a = reg.add(registry.Op("first", lambda data: data, doc="original."))
    reg.add(registry.Op("second", lambda data: -data, doc="usurper."))
    reg._shadows.append(("first", "first", "second"))
    assert "REG008" in rules(lint_registry(registry=reg))


def test_register_records_shadows():
    before = list(registry.shadowed())
    ops_before = dict(registry._OPS)
    try:
        registry.register("_lintfix_shadow_victim",
                          doc="victim.")(lambda data: data)
        registry.register("_lintfix_other",
                          aliases=("_lintfix_shadow_victim",),
                          doc="shadows the victim via alias.")(
                              lambda data: -data)
        new = [s for s in registry.shadowed() if s not in before]
        assert ("_lintfix_shadow_victim", "_lintfix_shadow_victim",
                "_lintfix_other") in new
    finally:
        registry._OPS.clear()
        registry._OPS.update(ops_before)
        registry._SHADOWS[:] = before


def test_reg009_missing_docstring_and_suppression():
    reg = FakeReg()
    reg.add(registry.Op("nodoc", lambda data: data))
    assert rules(lint_registry(registry=reg)) == {"REG009"}

    def suppressed_fn(data):
        # mxlint: disable=REG009
        return data

    reg2 = FakeReg()
    reg2.add(registry.Op("nodoc2", suppressed_fn))
    assert lint_registry(registry=reg2) == []


def test_reg010_zero_coverage():
    reg = FakeReg()
    reg.add(registry.Op("uncovered", lambda data: data, doc="fixture."))
    assert rules(lint_registry(registry=reg, coverage_map={})) == {"REG010"}
    # an alias entry in the map covers the canonical name too
    reg.add(reg.get("uncovered"), "uncovered_alias")
    assert lint_registry(
        registry=reg,
        coverage_map={"uncovered_alias": "somewhere"}) == []


def test_reg011_introspection_fallback():
    class Weird:
        __signature__ = "not-a-signature"

        def __call__(self, data):
            return data

    reg = FakeReg()
    reg.add(registry.Op("weird", Weird(), doc="uninspectable callable."))
    assert "REG011" in rules(lint_registry(registry=reg))


def test_fn_params_robust_to_partial():
    def base(data, other, alpha=1.0, beta=2.0):
        """Partial-registered fixture."""
        return data + other * alpha * beta

    op = registry.Op("partial_op", functools.partial(base, beta=3.0),
                     arg_names=["data", "other"], scalar_args=("alpha",))
    assert op.fn_params == ["data", "other", "alpha"]
    assert not op.fn_params_fallback
    reg = FakeReg()
    reg.add(op)
    assert lint_registry(registry=reg) == []


# ---------------------------------------------------------------------------
# graph pass
# ---------------------------------------------------------------------------
def test_grf001_dead_output():
    data = sym.var("data")
    parts = sym.SliceChannel(data, num_outputs=3, name="dead_split")
    findings = lint_graph(parts[0], check_consts=False)
    assert [f.rule_id for f in findings] == ["GRF001", "GRF001"]
    # consuming every output silences the rule
    s = parts[0] + parts[1] + parts[2]
    assert lint_graph(s, check_consts=False) == []


def test_grf002_nondiff_on_grad_path():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="g2_fc")
    cut = sym.argmax(fc, axis=1, name="g2_argmax")
    loss = sym.MakeLoss(cut, name="g2_loss")
    findings = lint_graph(loss, check_consts=False)
    assert rules(findings) == {"GRF002"}
    assert findings[0].subject == "g2_argmax"
    # no loss head -> predict-only graph, rule stays quiet
    assert lint_graph(cut, check_consts=False) == []
    # differentiable path to the loss head is fine
    assert lint_graph(sym.MakeLoss(fc, name="g2_ok"),
                      check_consts=False) == []


def test_grf003_aux_read_outside_train():
    data = sym.var("data")
    bn = sym.BatchNorm(data, name="g3_bn")
    aux_nodes = [n for n in bn._nodes() if n.op is None and n._is_aux]
    assert aux_nodes
    leaked = bn + Symbol([(aux_nodes[0], 0)])
    findings = lint_graph(leaked, check_consts=False)
    assert rules(findings) == {"GRF003"}
    assert lint_graph(bn, check_consts=False) == []


def test_grf004_float64_promotion():
    a = sym.var("a", dtype="float64")
    b = sym.var("b")
    findings = lint_graph(a * b, check_consts=False)
    assert rules(findings) == {"GRF004"}
    # all-f32 graph is silent
    assert lint_graph(sym.var("x") * sym.var("y"), check_consts=False) == []
    # explicit f64 Cast from f32 is flagged too
    assert rules(lint_graph(sym.Cast(sym.var("z"), dtype="float64"),
                            check_consts=False)) == {"GRF004"}


def test_grf005_static_reshape():
    data = sym.var("data")
    bad = sym.Reshape(data, shape=(32, 100), name="g5_bad")
    assert rules(lint_graph(bad, check_consts=False)) == {"GRF005"}
    ok = sym.Reshape(data, shape=(0, -1), name="g5_ok")
    assert lint_graph(ok, check_consts=False) == []


def test_grf005_node_level_suppression():
    data = sym.var("data")
    bad = sym.Reshape(data, shape=(32, 100), name="g5_muted")
    bad._set_attr(__mxlint_disable__="GRF005")
    assert lint_graph(bad, check_consts=False) == []


def test_grf006_large_baked_constant():
    big = np.ones((512, 600), np.float32)   # ~1.2 MiB
    ops_before = dict(registry._OPS)
    try:
        registry.register("_lintfix_bigconst",
                          doc="adds a >1MiB closure constant.")(
                              lambda data: data + jnp.asarray(big).sum())
        s = _sym_invoke(registry.get("_lintfix_bigconst"),
                        "_lintfix_bigconst", (sym.var("data"),), {})
        findings = lint_graph(s, shapes={"data": (4, 8)})
        assert rules(findings) == {"GRF006"}
        assert "MiB" in findings[0].message
    finally:
        registry._OPS.clear()
        registry._OPS.update(ops_before)


# ---------------------------------------------------------------------------
# source pass
# ---------------------------------------------------------------------------
def test_src001_scalar_capture():
    src = "loss = net.forward(batch)\nval = loss.item()\n"
    findings = lint_source(src, filename="train.py")
    assert rules(findings) == {"SRC001"}
    assert findings[0].subject == "train.py:2"
    # float() over an array expression is the same trap
    assert rules(lint_source("x = float(net(y))\n")) == {"SRC001"}


def test_src002_shape_branch():
    src = "if x.shape[0] > 16:\n    y = f(x)\nwhile x.size > 1:\n    x = g(x)\n"
    findings = lint_source(src)
    assert [f.rule_id for f in findings] == ["SRC002", "SRC002"]


def test_src_inline_suppression_and_clean():
    src = "v = loss.item()  # mxlint: disable=SRC001\n"
    assert lint_source(src) == []
    clean = "y = net(x)\nz = y + 1\n"
    assert lint_source(clean) == []


def test_src003_host_normalize_variants():
    """Host-side mean/std normalization is flagged with the fused
    device-tail suggestion (PR 3)."""
    # the spelled-out idiom
    assert rules(lint_source("x = (img - rgb_mean) / rgb_std\n")) == \
        {"SRC003"}
    # normalize helpers
    assert rules(lint_source("y = mx.image.color_normalize(img, m, s)\n")) \
        == {"SRC003"}
    assert rules(lint_source("aug = ColorNormalizeAug(mean, std)\n")) == \
        {"SRC003"}
    # iterator factories given mean/std without the device tail
    src = "it = mx.io.ImageRecordIter(path_imgrec=p, mean_r=123.0)\n"
    findings = lint_source(src)
    assert rules(findings) == {"SRC003"}
    assert "device_tail" in findings[0].message


def test_src003_clean_cases():
    # device_tail=True is exactly the fix — no finding
    ok = "it = ImageRecordIter(path_imgrec=p, mean_r=1.0, " \
         "device_tail=True)\n"
    assert lint_source(ok) == []
    # unrelated subtraction/division
    assert lint_source("z = (a - b) / c\n") == []
    # suppression works
    assert lint_source("x = (v - mean) / std  "
                       "# mxlint: disable=SRC003\n") == []


def test_doc001_rule_table_in_sync():
    """Every registered rule has a docs/analysis.md row (and the check is
    part of --self-check, so a new rule cannot land undocumented)."""
    from mxnet_tpu.analysis import lint_rule_docs
    assert lint_rule_docs() == []


# ---------------------------------------------------------------------------
# hooks: Symbol.lint / Module.lint / simple_bind(lint=True)
# ---------------------------------------------------------------------------
def _mlp():
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=8, name="lint_fc1")
    a = sym.Activation(h, act_type="relu", name="lint_relu")
    out = sym.FullyConnected(a, num_hidden=4, name="lint_fc2")
    return sym.SoftmaxOutput(out, name="lint_softmax")


def test_clean_graph_is_silent_end_to_end():
    net = _mlp()
    assert net.lint(shapes={"data": (2, 16)}) == []


def test_module_lint_uses_bound_shapes():
    mod = mx.module.Module(_mlp(), data_names=("data",),
                           label_names=("lint_softmax_label",))
    findings = mod.lint()          # unbound: shape-dependent rules skip
    assert findings == []
    mod.bind(data_shapes=[("data", (2, 16))],
             label_shapes=[("lint_softmax_label", (2,))])
    assert mod.lint() == []


def test_simple_bind_lint_raises_on_error():
    data = sym.var("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="sb_fc")
    loss = sym.MakeLoss(sym.argmax(fc, axis=1, name="sb_argmax"),
                        name="sb_loss")
    with pytest.raises(MXNetError, match="GRF002"):
        loss.simple_bind(mx.cpu(), lint=True, data=(2, 8))
    # without lint the (broken) graph still binds as before
    ex = loss.simple_bind(mx.cpu(), data=(2, 8))
    assert ex is not None


def test_simple_bind_lint_warns_on_warning():
    data = sym.var("data")
    r = sym.Reshape(data, shape=(2, 16), name="sb_reshape")
    with pytest.warns(UserWarning, match="GRF005"):
        ex = r.simple_bind(mx.cpu(), lint=True, data=(2, 4, 4))
    assert ex.forward()[0].shape == (2, 16)


# ---------------------------------------------------------------------------
# reporters + CLI (satellite: CI tier-1 self-check)
# ---------------------------------------------------------------------------
def test_reporters_and_exit_codes():
    reg = FakeReg()
    reg.add(registry.Op("nodoc", lambda data: data))
    findings = lint_registry(registry=reg)
    text = render_text(findings)
    assert "REG009" in text and "nodoc" in text
    payload = json.loads(render_json(findings))
    assert payload["version"] == 1
    assert payload["findings"][0]["rule"] == "REG009"
    assert payload["counts"] == {"warning": 1}
    assert exit_code(findings, strict=False) == 0
    assert exit_code(findings, strict=True) == 1
    assert exit_code([], strict=True) == 0


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-m", "mxnet_tpu.analysis"]
                          + list(args), capture_output=True, text=True,
                          cwd=REPO, env=env, timeout=300)


def test_self_check_cli_clean_on_shipped_registry():
    """CI gate: new op registrations that break a registry invariant (or
    land without docs/coverage) fail here before anything executes."""
    proc = _run_cli("--self-check")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_source_lint_json(tmp_path):
    script = tmp_path / "bad_train.py"
    script.write_text("for b in loader:\n"
                      "    v = model(b).item()\n"
                      "    if b.shape[0] < 8:\n"
                      "        break\n")
    proc = _run_cli(str(script), "--json", "--strict")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    got = {f["rule"] for f in payload["findings"]}
    assert got == {"SRC001", "SRC002"}
