"""mxnet_tpu.telemetry: unified fleet observability (tier-1, ISSUE 9).

Contract points:
(a) the metrics registry: instruments + weakly-held collectors, valid
    Prometheus text exposition, versioned JSON round-tripped through
    tools/parse_log.py (newer schema refused, not misparsed);
(b) the flight recorder: mmap ring ordering/truncation/CRC, the
    per-step progress cursor, and — the point of the thing — events
    surviving a SIGKILL, read back by the postmortem CLI;
(c) chrome-trace hygiene: dumps() schema (ph/ts/pid/tid), the bounded
    event buffer with a dropped-event count, Counter/Marker thread
    safety under concurrent emitters;
(d) trace correlation: a trace context round-trips over a REAL PS
    push/pull (worker span id == server-side flight record id), chaos
    faults stamp instant events + ring records at their probe sites,
    and tools/trace_merge.py aligns per-rank traces + rings into one
    timeline;
(e) the serving /metrics route returns parseable Prometheus text;
    DataParallelTrainer.fit dumps the versioned metrics JSON;
(f) the headline: a 2-worker + 1-server fleet with a chaos SIGKILL of
    the server mid-training yields a merged fleet chrome trace where
    the killed push's worker span links to the server-side fault event
    (same trace_id), and a postmortem recovered from the dead server's
    mmap ring showing its last applied (rank, push_step).
"""
import ast
import gc
import glob
import http.client
import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, kvstore_ps, profiler, telemetry
from mxnet_tpu.resilience import Fault, chaos
from mxnet_tpu.telemetry import flight, trace
from mxnet_tpu.telemetry.metrics import MetricsRegistry

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    yield
    telemetry.disable()
    chaos.uninstall()
    if profiler.state() == "run":
        profiler.set_state("stop")


def _cpu_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_CHAOS", None)
    env.pop("MXTPU_TELEMETRY_DIR", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update({k: str(v) for k, v in extra.items()})
    return env


# ---------------------------------------------------------------------------
# (a) metrics registry
# ---------------------------------------------------------------------------
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+-]+(nan|inf)?$")


def _assert_prometheus_text(text):
    """Every non-comment, non-blank line must be a valid sample line."""
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), "bad exposition line: %r" % line


def test_registry_instruments_and_prometheus_text():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "requests seen")
    c.inc(3, model="a", tier="gold")
    c.inc(model="b")
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("t_depth")
    g.set(7)
    g.inc(2)
    h = reg.histogram("t_lat_ms", "latency")
    for i in range(200):
        h.observe(float(i))
    # re-registration is idempotent; a kind conflict is an error
    assert reg.counter("t_requests_total") is c
    with pytest.raises(ValueError):
        reg.gauge("t_requests_total")
    text = reg.prometheus_text()
    _assert_prometheus_text(text)
    assert '# TYPE t_requests_total counter' in text
    assert 't_requests_total{model="a",tier="gold"} 3' in text
    assert "t_depth 9" in text
    assert '# TYPE t_lat_ms summary' in text
    assert 't_lat_ms{quantile="0.5"}' in text
    assert "t_lat_ms_count 200" in text
    p50, p99 = h.quantiles()
    assert p50 == pytest.approx(99.0, abs=2)
    assert p99 == pytest.approx(197.0, abs=3)


def test_histogram_reservoir_bounds_window():
    reg = MetricsRegistry()
    h = reg.histogram("t_win", reservoir=64)
    for i in range(1000):
        h.observe(float(i))
    p50, _ = h.quantiles()
    # old samples aged out: the window covers [936, 999], not [0, 999]
    assert p50 > 900
    (_, cell), = h.samples()
    assert cell["count"] == 1000 and cell["sum"] == sum(range(1000))


def test_histogram_percentile_accuracy_after_wrap():
    """After the reservoir wraps, p50/p99 must track the NEWEST
    ``reservoir`` observations accurately — not a mixture with aged-out
    samples (ISSUE-10 satellite: the PR-9 hammer covered Counter, not
    Histogram)."""
    reg = MetricsRegistry()
    h = reg.histogram("t_acc", reservoir=256)
    # first era: uniform 0..999 — fully aged out by the second era
    for i in range(1000):
        h.observe(float(i))
    # second era: exactly 256 samples of a known uniform grid 0..255
    for i in range(256):
        h.observe(float(i))
    p50, p99 = h.quantiles()
    # nearest-rank over 0..255: p50 = 128, p99 = 252 (+-1 for rounding)
    assert abs(p50 - 127.5) <= 1.0
    assert abs(p99 - 252.45) <= 1.0
    (_, cell), = h.samples()
    assert cell["count"] == 1256                       # exact lifetime
    assert cell["sum"] == sum(range(1000)) + sum(range(256))
    assert cell["p50"] == p50 and cell["p99"] == p99
    # per-label-set reservoirs are independent
    h.observe(1e6, shard="other")
    assert h.quantiles() == (p50, p99)


def test_histogram_concurrent_observe_four_threads():
    """4 threads observing concurrently (the serving-handler pattern):
    no update lost, no exception, percentiles land inside the observed
    range — under a concurrent scrape loop too."""
    reg = MetricsRegistry()
    h = reg.histogram("t_conc", reservoir=512)
    n_per, errs = 5000, []

    def worker(tid):
        try:
            for i in range(n_per):
                h.observe(float(tid * n_per + i), thread=str(tid % 2))
        except Exception as e:   # pragma: no cover - the failure mode
            errs.append(e)

    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            reg.prometheus_text()
            reg.to_json()

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(4)]
    s = threading.Thread(target=scraper)
    s.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    s.join(timeout=60)
    assert not errs
    total = {}
    for labels, cell in h.samples():
        total[labels["thread"]] = cell
        lo, hi = 0.0, 4.0 * n_per
        assert lo <= cell["p50"] <= hi
        assert lo <= cell["p99"] <= hi
        assert cell["p50"] <= cell["p99"]
    # exactly-once accounting across the 4 threads (2 per label set)
    assert total["0"]["count"] == total["1"]["count"] == 2 * n_per
    assert total["0"]["sum"] + total["1"]["sum"] == \
        sum(range(4 * n_per))


def test_collector_weakref_drops_dead_source():
    reg = MetricsRegistry()

    class Src:
        def samples(self):
            return [("t_coll_gauge", {"who": "x"}, 1.0)]

    src = Src()
    reg.register_collector(src.samples, name="src")
    assert "t_coll_gauge" in reg.prometheus_text()
    del src
    gc.collect()
    assert "t_coll_gauge" not in reg.prometheus_text()
    # dict-returning and raising collectors are both handled
    reg.register_collector(lambda: {"t_flat": 2})
    reg.register_collector(lambda: 1 / 0)
    text = reg.prometheus_text()
    assert "t_flat 2" in text


def test_metrics_json_roundtrip_and_parse_log(tmp_path):
    reg = MetricsRegistry()
    reg.counter("t_total").inc(5, rank="0")
    reg.histogram("t_ms").observe(4.0)
    path = str(tmp_path / "metrics.json")
    payload = reg.dump_json(path, source="test")
    assert payload["schema_version"] == telemetry.SCHEMA_VERSION
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "parse_log.py"),
         path], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert 't_total{rank="0"}\t5' in out.stdout
    assert "t_ms_p50\t4" in out.stdout
    # a NEWER schema version is refused, never misparsed
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    try:
        import parse_log
        with pytest.raises(ValueError):
            parse_log.parse_metrics_json({"schema_version": 999,
                                          "metrics": {}})
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# (b) flight recorder
# ---------------------------------------------------------------------------
def test_flight_ring_order_wrap_truncation_cursor(tmp_path):
    path = str(tmp_path / "r.mxring")
    ring = flight.FlightRecorder(path, slots=8, slot_bytes=128,
                                 meta={"rank": 3, "role": "worker"})
    for i in range(20):            # wraps: only the last 8 survive
        ring.record("ev", i=i)
    ring.record("big", blob="x" * 500)   # oversized -> truncated marker
    ring.set_cursor(41)
    ring.close()
    meta, events = flight.read_ring(path)
    assert meta["rank"] == 3 and meta["role"] == "worker"
    assert meta["cursor_step"] == 41 and meta["cursor_ts_ns"] > 0
    assert [e["i"] for e in events[:-1]] == list(range(13, 20))
    assert events[-1]["kind"] == "big" and events[-1]["truncated"] == 1
    assert "blob" not in events[-1]
    assert all("ts_ns" in e and "wall_ns" in e for e in events[:-1])


def test_flight_ring_survives_sigkill(tmp_path):
    d = str(tmp_path)
    src = (
        "import os, signal\n"
        "from mxnet_tpu import telemetry\n"
        "telemetry.enable(%r, rank=5, role='worker')\n"
        "for i in range(30):\n"
        "    telemetry.record('ps.apply', rank=1, step=i, key='w0')\n"
        "telemetry.cursor(29)\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n" % d)
    proc = subprocess.run([sys.executable, "-c", src], env=_cpu_env(),
                          timeout=120)
    assert proc.returncode == -signal.SIGKILL
    report = telemetry.postmortem(d)
    (ring,) = report["rings"]
    assert ring["meta"]["rank"] == 5
    assert ring["meta"]["cursor_step"] == 29
    assert ring["last_apply"]["step"] == 29
    assert len(ring["events"]) > 0


def test_postmortem_cli(tmp_path):
    d = str(tmp_path)
    telemetry.enable(d, rank=0, role="server")
    telemetry.record("ps.apply", rank=2, step=7, key="w1")
    chaos.install([Fault("kvstore.snapshot", 1, "raise")])
    with pytest.raises(chaos.ChaosError):
        chaos.maybe_inject("kvstore.snapshot")
    telemetry.disable()
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "postmortem", d,
         "--json"], capture_output=True, text=True, timeout=120,
        env=_cpu_env(), cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    report = json.loads(out.stdout)
    (ring,) = report["rings"]
    assert ring["last_apply"]["rank"] == 2
    assert ring["last_apply"]["step"] == 7
    assert ring["faults"][0]["site"] == "kvstore.snapshot"
    # human rendering names the essentials too
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "postmortem", d],
        capture_output=True, text=True, timeout=120, env=_cpu_env(),
        cwd=_ROOT)
    assert "last applied push: rank=2 push_step=7" in out.stdout
    assert "FAULT kvstore.snapshot@1" in out.stdout
    # empty dir -> rc 1
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "postmortem",
         str(tmp_path / "nothing")], capture_output=True, text=True,
        timeout=120, env=_cpu_env(), cwd=_ROOT)
    assert out.returncode == 1


# ---------------------------------------------------------------------------
# (c) chrome-trace hygiene
# ---------------------------------------------------------------------------
def test_chrome_trace_schema_and_metadata():
    profiler.set_state("run")
    with profiler.Task("work"):
        time.sleep(0.001)
    domain = profiler.Domain("t")
    domain.new_counter("c", 1).increment()
    domain.new_marker("m").mark()
    profiler.record_instant("inst", "cat", args={"k": 1})
    profiler.set_metadata(rank=4)
    doc = json.loads(profiler.dumps())
    profiler.set_state("stop")
    events = doc["traceEvents"]
    assert events and doc["displayTimeUnit"] == "ms"
    for ev in events:
        assert ev["ph"] in ("X", "i", "C", "M")
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "X":
            assert "dur" in ev and "tid" in ev
        if ev["ph"] == "i":
            assert "tid" in ev and ev["s"] == "p"
    meta = doc["metadata"]
    assert meta["rank"] == 4
    assert meta["pid"] == os.getpid()
    assert meta["perf_origin_ns"] > 0
    assert meta["dropped_events"] == 0


def test_profiler_event_buffer_bounded(monkeypatch):
    monkeypatch.setattr(profiler, "_MAX_EVENTS", 10)
    profiler.set_state("run")
    for i in range(50):
        profiler.record_instant("e%d" % i, "cat")
    assert profiler.dropped_events() == 40
    doc = json.loads(profiler.dumps())
    profiler.set_state("stop")
    assert len(doc["traceEvents"]) == 10
    assert doc["metadata"]["dropped_events"] == 40
    assert doc["metadata"]["event_cap"] == 10


def test_counter_marker_thread_safety_under_dumps():
    profiler.set_state("run")
    domain = profiler.Domain("t")
    counter = domain.new_counter("n", 0)
    marker = domain.new_marker("m")
    stop = threading.Event()
    errors = []

    def emit():
        try:
            for _ in range(2000):
                counter.increment()
                marker.mark()
        except Exception as e:   # pragma: no cover - the failure mode
            errors.append(e)

    def drain():
        while not stop.is_set():
            json.loads(profiler.dumps(reset=True))

    drainer = threading.Thread(target=drain)
    drainer.start()
    threads = [threading.Thread(target=emit) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    drainer.join()
    profiler.set_state("stop")
    assert not errors
    # no lost increments: 4 threads x 2000 atomic +1s
    assert counter._value == 8000


# ---------------------------------------------------------------------------
# (d) trace correlation
# ---------------------------------------------------------------------------
def test_trace_wire_roundtrip():
    ctx = trace.SpanContext(rank=3, incarnation="abc")
    back = trace.from_wire(trace.to_wire(ctx))
    assert (back.trace_id, back.span_id, back.parent_id, back.rank,
            back.incarnation) == (ctx.trace_id, ctx.span_id, None, 3,
                                  "abc")
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    with pytest.raises(ValueError):
        trace.from_wire((99, "x"))


def test_trace_context_roundtrip_over_real_ps(tmp_path):
    telemetry.enable(str(tmp_path), rank=0, role="worker")
    profiler.set_state("run")
    srv = kvstore_ps.PSServer(port=0)
    cli = kvstore_ps.PSClient("127.0.0.1", srv.port, rank=0)
    try:
        assert cli.clock_offset_ns is not None   # sync_clock ran
        cli.init_array("k", np.zeros(8, np.float32))
        cli.push_array("k", np.ones(8, np.float32), step=1)
        cli.pull_array("k")
    finally:
        cli.close()
        srv.stop()
    doc = json.loads(profiler.dumps())
    profiler.set_state("stop")
    telemetry.disable()
    push_spans = [e for e in doc["traceEvents"] if e["name"] == "ps.push"
                  and "cmd" in e.get("args", {}) is not None]
    client_push = [e for e in push_spans if "rank" in e["args"]
                   and e["args"].get("incarnation")]
    assert client_push, "client push span missing"
    tid = client_push[0]["args"]["trace_id"]
    # the server's handling span carries the SAME trace id (in-process
    # server: both sides land in one trace buffer)
    server_side = [e for e in push_spans
                   if e["args"]["trace_id"] == tid and e is not
                   client_push[0]]
    assert server_side, "server-side span not linked to the client push"
    # ... and so does the flight-ring apply record
    (ring_file,) = glob.glob(str(tmp_path / "*.mxring"))
    _, events = flight.read_ring(ring_file)
    applies = [e for e in events if e["kind"] == "ps.apply"]
    assert applies and applies[-1]["trace_id"] == tid
    assert applies[-1]["rank"] == 0 and applies[-1]["step"] == 1
    # clock metadata landed for trace_merge
    assert "ps_clock_offset_ns" in doc["metadata"]


def test_chaos_fault_stamps_instant_event_and_ring(tmp_path):
    telemetry.enable(str(tmp_path), rank=1, role="worker")
    profiler.set_state("run")
    chaos.install([Fault("trainer.step", 3, "raise")])
    for step in (1, 2):
        chaos.maybe_inject("trainer.step", step)
    with pytest.raises(chaos.ChaosError):
        chaos.maybe_inject("trainer.step", 3, ctx="ctx-object")
    doc = json.loads(profiler.dumps())
    profiler.set_state("stop")
    instants = [e for e in doc["traceEvents"]
                if e["name"] == "chaos.trainer.step"]
    assert len(instants) == 1 and instants[0]["ph"] == "i"
    assert instants[0]["args"]["at"] == 3
    assert instants[0]["args"]["action"] == "raise"
    (ring_file,) = glob.glob(str(tmp_path / "*.mxring"))
    _, events = flight.read_ring(ring_file)
    faults = [e for e in events if e["kind"] == "chaos.fault"]
    assert faults and faults[0]["site"] == "trainer.step"
    assert telemetry.registry().counter(
        "mxtpu_chaos_faults_total").value(site="trainer.step",
                                          action="raise") >= 1
    telemetry.disable()


def test_trace_merge_aligns_ranks_and_rings(tmp_path):
    # two synthetic rank traces 1s apart in perf-origin, the worker
    # knowing its offset to the server's clock; one server ring event
    worker = {"traceEvents": [
        {"name": "ps.push", "cat": "ps", "ph": "X", "ts": 1000.0,
         "dur": 50.0, "pid": 1, "tid": 1, "args": {"trace_id": "t1"}}],
        "displayTimeUnit": "ms",
        "metadata": {"rank": 0, "perf_origin_ns": 5_000_000_000,
                     "ps_clock_offset_ns": 2_000_000_000}}
    server = {"traceEvents": [
        {"name": "apply", "cat": "ps", "ph": "X", "ts": 500.0,
         "dur": 10.0, "pid": 9, "tid": 2, "args": {}}],
        "displayTimeUnit": "ms",
        "metadata": {"rank": None, "role": "server",
                     "perf_origin_ns": 7_000_000_000}}
    wpath, spath = str(tmp_path / "w.json"), str(tmp_path / "s.json")
    json.dump(worker, open(wpath, "w"))
    json.dump(server, open(spath, "w"))
    ring = flight.FlightRecorder(str(tmp_path / "flight-server-1.mxring"),
                                 meta={"role": "server", "rank": None})
    ring.record("chaos.fault", site="kvstore.server_apply",
                trace_id="t1")
    ring.close()
    merged_path = str(tmp_path / "fleet.json")
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "trace_merge.py"),
         "-o", merged_path, wpath, spath, "--rings", str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.load(open(merged_path))
    by_name = {}
    for ev in doc["traceEvents"]:
        by_name.setdefault(ev["name"], []).append(ev)
    # worker event at abs 5e9 + 1e6 + 2e9 = 7.001e9; server at 7.0005e9:
    # after re-basing to the min the server apply precedes the push
    push, apply = by_name["ps.push"][0], by_name["apply"][0]
    assert apply["ts"] < push["ts"]
    assert push["ts"] - apply["ts"] == pytest.approx(500.0, abs=1.0)
    # distinct pids with process_name metadata, ring folded as instant
    assert push["pid"] != apply["pid"]
    assert "process_name" in by_name
    fault = by_name["chaos.fault"][0]
    assert fault["ph"] == "i" and fault["args"]["trace_id"] == "t1"
    merged_meta = doc["metadata"]["merged_from"]
    assert merged_meta["worker0"]["aligned"] is True
    assert doc["metadata"]["skipped_count"] == 0


def test_trace_merge_skips_torn_inputs_with_recorded_warning(tmp_path):
    """ISSUE-10 satellite regression test: a missing or torn per-rank
    trace/ring must be skipped with a recorded warning (surfaced in the
    merged metadata), not abort the whole merge — exactly the files a
    SIGKILLed rank leaves behind."""
    good = {"traceEvents": [
        {"name": "step", "cat": "t", "ph": "X", "ts": 10.0, "dur": 5.0,
         "pid": 1, "tid": 1}],
        "metadata": {"rank": 0, "perf_origin_ns": 1_000_000}}
    gpath = str(tmp_path / "good.json")
    json.dump(good, open(gpath, "w"))
    torn = str(tmp_path / "torn.json")
    with open(torn, "w") as f:
        f.write(json.dumps(good)[:40])          # mid-write crash
    wrong_shape = str(tmp_path / "list.json")
    json.dump([1, 2, 3], open(wrong_shape, "w"))
    missing = str(tmp_path / "never_written.json")
    # one good ring + one garbage ring
    ring = flight.FlightRecorder(str(tmp_path / "flight-worker0-1.mxring"),
                                 meta={"role": "worker", "rank": 0})
    ring.record("trainer.step", step=3)
    ring.close()
    bad_ring = str(tmp_path / "flight-worker1-2.mxring")
    with open(bad_ring, "wb") as f:
        f.write(b"NOTARING" + b"\x00" * 64)
    merged_path = str(tmp_path / "fleet.json")
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "trace_merge.py"),
         "-o", merged_path, gpath, torn, wrong_shape, missing,
         "--rings", str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "4 unreadable input(s) skipped" in out.stdout
    for name in ("torn.json", "list.json", "never_written.json"):
        assert name in out.stderr
    doc = json.load(open(merged_path))
    # the survivors merged: the good trace's event + the good ring's
    names = {e["name"] for e in doc["traceEvents"]}
    assert "step" in names and "trainer.step" in names
    # the skip count and per-file reasons are IN the merged output — a
    # partial merge can never pass as a complete one
    meta = doc["metadata"]
    assert meta["skipped_count"] == 4
    skipped_files = {s["file"] for s in meta["skipped"]}
    assert skipped_files == {"torn.json", "list.json",
                             "never_written.json",
                             os.path.basename(bad_ring)}
    assert all(s["error"] for s in meta["skipped"])
    # importable API agrees (tests call merge() directly)
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    try:
        import trace_merge
        doc2 = trace_merge.merge([gpath, missing])
        assert doc2["metadata"]["skipped_count"] == 1
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# (e) serving /metrics + trainer fit dump
# ---------------------------------------------------------------------------
def _hybrid_runner(seed=0):
    from mxnet_tpu.serving import ModelRunner
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return ModelRunner(net, buckets=(1, 4), example_shape=(8,))


def test_serving_metrics_route_parses_as_prometheus():
    from mxnet_tpu.serving import ModelFleet, Server
    fleet = ModelFleet(batch_timeout_ms=1.0)
    fleet.register("m", _hybrid_runner())
    server = Server(fleet, port=0)
    host, port = server.start()
    try:
        fleet.infer(np.zeros(8, np.float32), model="m")
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        _assert_prometheus_text(body)
        assert 'mxtpu_serving_requests_total{model="m"} 1' in body
        assert 'mxtpu_serving_breaker_state{model="m"} 0' in body
        assert "mxtpu_serving_modeled_hbm_total_bytes" in body
        conn.close()
    finally:
        server.drain(timeout=10)


def test_trainer_fit_dumps_versioned_metrics_json(tmp_path):
    from mxnet_tpu.parallel import DataParallelTrainer
    mx.random.seed(0)
    np.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05})
    x = np.random.rand(32, 10).astype(np.float32)
    y = np.random.randint(0, 4, 32).astype(np.int64)
    it = mx.io.NDArrayIter(x, y, batch_size=8)
    path = str(tmp_path / "metrics.json")
    trainer.fit(it, num_epoch=1, metrics_path=path)
    doc = json.load(open(path))
    assert doc["schema_version"] == telemetry.SCHEMA_VERSION
    assert doc["source"] == "trainer.fit"
    assert doc["step_count"] == 4
    assert doc["dispatch_stats"]["dispatched_steps"] == 4
    # the trainer's dispatch PipelineStats registered as a collector
    names = {s["labels"].get("name")
             for m in doc["metrics"].values() for s in m["samples"]}
    assert "engine.dispatch" in names
    # and the same document is parse_log-readable
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "parse_log.py"),
         path], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0 and "mxtpu_pipeline" in out.stdout


def test_telemetry_bench_keys():
    env = _cpu_env(MXTPU_TELE_BENCH_STEPS=40)
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry.bench"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["flight_recorder_write_ns"] > 0
    assert rec["metrics_scrape_ms"] > 0
    assert isinstance(rec["telemetry_overhead_gate_ok"], bool)
    # the <= 1% gate is asserted on the full-length bench run; at the
    # test's reduced step count only sanity-bound the number
    assert rec["telemetry_overhead_pct"] < 10.0


# ---------------------------------------------------------------------------
# (f) the headline: 2 workers + 1 server, chaos SIGKILL of the server
# ---------------------------------------------------------------------------
_SERVER_SRC = (
    "from mxnet_tpu.kvstore_server import _init_kvstore_server_module\n"
    "_init_kvstore_server_module()\n")

_WORKER_SRC = """\
import os, pickle, sys
import numpy as np
from mxnet_tpu import kvstore_ps, profiler, telemetry
from mxnet_tpu import optimizer as opt
port, outdir, steps, rank = (int(sys.argv[1]), sys.argv[2],
                             int(sys.argv[3]), int(sys.argv[4]))
telemetry.maybe_enable_from_env()
profiler.set_state('run')
profiler.set_metadata(role='worker', rank=rank)
cli = kvstore_ps.PSClient('127.0.0.1', port, rank=rank,
                          connect_retry_s=120)
if rank == 0:
    cli.request('set_optimizer', pickle.dumps(
        opt.create('sgd', learning_rate=0.1, momentum=0.9)))
keys = ['w0', 'w1']
rng = np.random.RandomState(11 + rank)
for k in keys:
    cli.init_array(k, rng.rand(32).astype(np.float32))
step = 0
for s in range(steps):
    for k in keys:
        step += 1
        g = rng.rand(32).astype(np.float32) - 0.5
        cli.push_array(k, g, step=step)
        telemetry.cursor(step)
cli.pull_array('w0')
with open(os.path.join(outdir, 'trace-rank%d.json' % rank), 'w') as f:
    f.write(profiler.dumps())
print('DONE', step, flush=True)
cli.close()
"""


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_fleet_sigkill_server_trace_and_postmortem(tmp_path):
    """The ISSUE-9 acceptance test.  A 2-worker + 1-server fleet is run
    with telemetry armed; the chaos harness SIGKILLs the server at
    applied push #13; the server rank is respawned over the same state
    dir (what launch.py --restart-failed does) and both workers finish
    through the failover.  Then:

    (a) the merged fleet chrome trace (trace_merge over both worker
        traces + every flight ring) contains the server-side fault
        instant event, sharing its trace_id with the killed push's
        worker-side span — the worker→server link;
    (b) the postmortem recovered from the DEAD server's mmap ring shows
        its last applied (rank, push_step) and the fault.
    """
    tele_dir = str(tmp_path / "tele")
    os.makedirs(tele_dir)
    state = str(tmp_path / "state")
    port = _free_port()
    senv = _cpu_env(DMLC_ROLE="server", MXTPU_PS_PORT=port,
                    MXTPU_PS_STATE_DIR=state, MXTPU_PS_SNAPSHOT_EVERY=5,
                    MXTPU_HEARTBEAT_INTERVAL_S=0,
                    MXTPU_TELEMETRY_DIR=tele_dir,
                    MXTPU_CHAOS="kvstore.server_apply:13:kill")
    server = subprocess.Popen([sys.executable, "-c", _SERVER_SRC],
                              env=senv, stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
    workers = [subprocess.Popen(
        [sys.executable, "-c", _WORKER_SRC, str(port), tele_dir, "10",
         str(rank)],
        env=_cpu_env(MXTPU_PS_RETRIES=12, MXTPU_TELEMETRY_DIR=tele_dir,
                     DMLC_WORKER_ID=rank),   # what launch.py exports
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for rank in (0, 1)]
    try:
        # the chaos kill fires mid-run; respawn over the SAME state dir
        assert server.wait(timeout=300) == -signal.SIGKILL
        senv.pop("MXTPU_CHAOS")
        server = subprocess.Popen([sys.executable, "-c", _SERVER_SRC],
                                  env=senv, stdout=subprocess.DEVNULL,
                                  stderr=subprocess.DEVNULL)
        for rank, w in enumerate(workers):
            wout, werr = w.communicate(timeout=300)
            assert w.returncode == 0, werr[-2000:]
            assert "DONE 20" in wout
    finally:
        for w in workers:
            w.kill()
        server.terminate()
        try:
            server.wait(timeout=30)
        except subprocess.TimeoutExpired:
            server.kill()

    # -- (b) postmortem from the dead server's ring -----------------------
    rings = sorted(glob.glob(os.path.join(tele_dir, "flight-server*")))
    assert len(rings) == 2, "expected the dead and respawned server rings"
    dead = None
    for path in rings:
        _, events = flight.read_ring(path)
        if any(e["kind"] == "chaos.fault" for e in events):
            dead = (path, events)
    assert dead is not None, "no ring captured the chaos fault"
    dead_path, dead_events = dead
    (fault,) = [e for e in dead_events if e["kind"] == "chaos.fault"]
    assert fault["site"] == "kvstore.server_apply"
    killed_rank, killed_step, killed_key = ast.literal_eval(fault["ctx"])
    applies = [e for e in dead_events if e["kind"] == "ps.apply"]
    assert len(applies) == 12          # 13th was the killed one
    last = applies[-1]
    assert last["step"] is not None and last["rank"] in (0, 1)
    # the CLI tells the same story
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "postmortem",
         tele_dir], capture_output=True, text=True, timeout=120,
        env=_cpu_env(), cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "last applied push: rank=%s push_step=%s" \
        % (last["rank"], last["step"]) in out.stdout
    assert "FAULT kvstore.server_apply@13 action=kill" in out.stdout
    # worker rings carry the progress cursor
    wrings = glob.glob(os.path.join(tele_dir, "flight-worker*"))
    assert len(wrings) == 2
    for path in wrings:
        meta, _ = flight.read_ring(path)
        assert meta["cursor_step"] == 20

    # -- (a) merged fleet trace: worker span <-> server fault link --------
    traces = [os.path.join(tele_dir, "trace-rank%d.json" % r)
              for r in (0, 1)]
    merged_path = os.path.join(tele_dir, "fleet.json")
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "trace_merge.py"),
         "-o", merged_path] + traces + ["--rings", tele_dir],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.load(open(merged_path))
    faults = [e for e in doc["traceEvents"]
              if e["name"] == "chaos.fault" and e["ph"] == "i"]
    assert faults, "fault instant event missing from the merged trace"
    fault_tid = faults[0]["args"]["trace_id"]
    # the killed push's span in the WORKER trace shares the trace id the
    # dead server recorded for the fault: worker -> server, linked
    killed_worker_spans = [
        e for e in doc["traceEvents"]
        if e["name"] == "ps.push" and e.get("args", {})
        .get("trace_id") == fault_tid and e["ph"] == "X"]
    assert killed_worker_spans, \
        "killed push's worker span not linked to the server fault"
    assert killed_worker_spans[0]["args"]["rank"] == killed_rank
    # every merged member is clock-aligned (workers synced against the
    # server; server rings are the base timebase)
    merged_from = doc["metadata"]["merged_from"]
    assert all(m.get("aligned") for m in merged_from.values()), merged_from
    # applies recovered from the dead ring appear on the fleet timeline
    assert any(e["name"] == "ps.apply" for e in doc["traceEvents"])
