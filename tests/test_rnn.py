"""RNN stack tests: fused op vs torch oracle, gluon.rnn, legacy mx.rnn
(reference: tests/python/unittest/test_gluon_rnn.py, test_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.ops import rnn as rnn_ops


def _pack_torch(tnet, num_layers, bidirectional):
    """Pack torch RNN params into the cuDNN flat layout the RNN op expects."""
    chunks_w, chunks_b = [], []
    sufs = ["", "_reverse"] if bidirectional else [""]
    for layer in range(num_layers):
        for suf in sufs:
            chunks_w.append(getattr(
                tnet, "weight_ih_l%d%s" % (layer, suf)).detach().numpy().ravel())
            chunks_w.append(getattr(
                tnet, "weight_hh_l%d%s" % (layer, suf)).detach().numpy().ravel())
    for layer in range(num_layers):
        for suf in sufs:
            chunks_b.append(getattr(
                tnet, "bias_ih_l%d%s" % (layer, suf)).detach().numpy().ravel())
            chunks_b.append(getattr(
                tnet, "bias_hh_l%d%s" % (layer, suf)).detach().numpy().ravel())
    return np.concatenate(chunks_w + chunks_b).astype(np.float32)


@pytest.mark.parametrize("mode,bidir", [
    ("lstm", False), ("lstm", True), ("gru", True), ("rnn_tanh", True),
    ("rnn_relu", False)])
def test_rnn_op_vs_torch(mode, bidir):
    """The fused RNN op matches torch's cuDNN-layout recurrences
    (reference numerics: src/operator/rnn_impl.h)."""
    torch = pytest.importorskip("torch")
    T, B, I, H, L = 5, 3, 4, 6, 2
    cls = {"lstm": torch.nn.LSTM, "gru": torch.nn.GRU,
           "rnn_tanh": torch.nn.RNN, "rnn_relu": torch.nn.RNN}[mode]
    kwargs = {"nonlinearity": mode[4:]} if mode.startswith("rnn_") else {}
    torch.manual_seed(0)
    tnet = cls(I, H, num_layers=L, bidirectional=bidir, **kwargs)
    flat = _pack_torch(tnet, L, bidir)
    assert flat.size == rnn_ops.rnn_param_size(H, I, L, mode, bidir)

    rng = np.random.RandomState(0)
    x = rng.randn(T, B, I).astype(np.float32)
    d = 2 if bidir else 1
    h0 = np.zeros((L * d, B, H), np.float32)
    args = [mx.nd.array(x), mx.nd.array(flat), mx.nd.array(h0)]
    if mode == "lstm":
        args.append(mx.nd.array(np.zeros((L * d, B, H), np.float32)))
    out = mx.nd.RNN(*args, state_size=H, num_layers=L, mode=mode,
                    bidirectional=bidir)
    tout, _ = tnet(torch.from_numpy(x))
    np.testing.assert_allclose(out.asnumpy(), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_rnn_op_state_outputs():
    T, B, I, H, L = 4, 2, 3, 5, 1
    flat_size = rnn_ops.rnn_param_size(H, I, L, "lstm", False)
    rng = np.random.RandomState(1)
    out, h, c = mx.nd.RNN(
        mx.nd.array(rng.randn(T, B, I).astype(np.float32)),
        mx.nd.array(rng.randn(flat_size).astype(np.float32) * 0.1),
        mx.nd.array(np.zeros((L, B, H), np.float32)),
        mx.nd.array(np.zeros((L, B, H), np.float32)),
        state_size=H, num_layers=L, mode="lstm", state_outputs=True)
    assert out.shape == (T, B, H)
    assert h.shape == (L, B, H) and c.shape == (L, B, H)
    np.testing.assert_allclose(out.asnumpy()[-1], h.asnumpy()[0], rtol=1e-5)


def test_gluon_lstm_layer_grad():
    lstm = gluon.rnn.LSTM(8, num_layers=2, bidirectional=True, dropout=0.0)
    lstm.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(5, 3, 4).astype(np.float32))
    out = lstm(x)
    assert out.shape == (5, 3, 16)
    with mx.autograd.record():
        y = mx.nd.sum(lstm(x))
    y.backward()
    params = lstm.collect_params()
    g = params[list(params.keys())[0]].grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_gluon_lstm_layer_ntc_and_states():
    lstm = gluon.rnn.LSTM(6, layout="NTC")
    lstm.initialize()
    x = mx.nd.array(np.zeros((3, 5, 4), np.float32))
    out, states = lstm(x, lstm.begin_state(3))
    assert out.shape == (3, 5, 6)
    assert states[0].shape == (1, 3, 6) and states[1].shape == (1, 3, 6)


def test_gluon_cells_unroll():
    cell = gluon.rnn.LSTMCell(6)
    cell.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(2, 5, 3).astype(np.float32))
    outputs, states = cell.unroll(5, x, layout="NTC")
    assert outputs.shape == (2, 5, 6)
    assert len(states) == 2

    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(4))
    stack.add(gluon.rnn.ResidualCell(gluon.rnn.GRUCell(4)))
    stack.initialize()
    o, s = stack.unroll(3, mx.nd.array(np.zeros((2, 3, 4), np.float32)),
                        layout="NTC")
    assert o.shape == (2, 3, 4) and len(s) == 3

    bi = gluon.rnn.BidirectionalCell(gluon.rnn.LSTMCell(4),
                                     gluon.rnn.LSTMCell(4))
    bi.initialize()
    o, s = bi.unroll(3, mx.nd.array(np.zeros((2, 3, 5), np.float32)),
                     layout="NTC")
    assert o.shape == (2, 3, 8)


def test_symbolic_lstm_cell_unroll_shapes():
    cell = mx.rnn.LSTMCell(num_hidden=24, prefix="lstm_")
    data = mx.sym.Variable("data")
    outputs, states = cell.unroll(4, data, layout="NTC", merge_outputs=True)
    args, outs, _ = outputs.infer_shape(data=(10, 4, 16))
    assert outs == [(10, 4, 24)]


def test_symbolic_fused_cell():
    fused = mx.rnn.FusedRNNCell(12, num_layers=2, mode="gru", prefix="g_")
    data = mx.sym.Variable("data")
    out, _ = fused.unroll(6, data, layout="NTC")
    _, outs, _ = out.infer_shape(data=(4, 6, 8))
    assert outs == [(4, 6, 12)]


def test_fused_unfuse_match():
    """FusedRNNCell and its unfused stack produce identical outputs given
    the same (unpacked) weights (reference: test_rnn.py test_unfuse)."""
    T, B, I, H = 3, 2, 4, 5
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="l_",
                                get_next_state=True)
    data = mx.sym.Variable("data")
    fout, _ = fused.unroll(T, data, layout="NTC")
    fex = fout.simple_bind(data=(B, T, I))
    rng = np.random.RandomState(0)
    flat = rng.randn(*fex.arg_dict["l_parameters"].shape).astype(np.float32) * 0.2
    fex.arg_dict["l_parameters"]._set_data(mx.nd.array(flat)._data)
    x = rng.randn(B, T, I).astype(np.float32)
    f_res = fex.forward(data=x)[0].asnumpy()

    stack = fused.unfuse()
    sout, _ = stack.unroll(T, data, layout="NTC", merge_outputs=True)
    sex = sout.simple_bind(data=(B, T, I))
    args = fused.unpack_weights({"l_parameters": mx.nd.array(flat)})
    for name, arr in args.items():
        sex.arg_dict[name]._set_data(arr._data)
    s_res = sex.forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(f_res, s_res, rtol=1e-4, atol=1e-5)


def test_bucket_sentence_iter_and_lm_training():
    """Bucketing LM converges (reference: tests/python/train/test_bucketing.py)."""
    vocab = 16
    rng = np.random.RandomState(2)
    # learnable pattern: next token = (token + 1) % vocab
    sents = []
    for _ in range(120):
        start = rng.randint(1, vocab)
        ln = rng.randint(2, 8)
        sents.append([(start + i) % vocab for i in range(ln)])
    it = mx.rnn.BucketSentenceIter(sents, batch_size=10, buckets=[4, 8])

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=12,
                                 name="embed")
        cell = mx.rnn.LSTMCell(num_hidden=16, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, embed, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, 16))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        return (mx.sym.SoftmaxOutput(pred, lab, name="softmax",
                                     use_ignore=True, ignore_label=-1),
                ("data",), ("softmax_label",))

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key,
                                 context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer_params={"learning_rate": 1.0})
    m = mx.metric.Perplexity(ignore_label=-1)
    ppl = []
    for epoch in range(4):
        it.reset()
        m.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            mod.update_metric(m, batch.label)
        ppl.append(m.get()[1])
    assert ppl[-1] < ppl[0] * 0.7, ppl


def test_fused_unpack_pack_roundtrip_multilayer():
    """pack(unpack(x)) == x for num_layers>=2 (regression: input-size
    inference in FusedRNNCell.unpack_weights)."""
    H, I, L = 5, 7, 2
    for mode, bidir in [("lstm", False), ("gru", True)]:
        fused = mx.rnn.FusedRNNCell(H, num_layers=L, mode=mode,
                                    bidirectional=bidir, prefix="f_")
        n = rnn_ops.rnn_param_size(H, I, L, mode, bidir)
        flat = np.arange(n, dtype=np.float32)
        args = fused.unpack_weights({"f_parameters": mx.nd.array(flat)})
        assert args["f_l0_i2h_weight"].shape[1] == I
        packed = fused.pack_weights(args)["f_parameters"].asnumpy()
        np.testing.assert_array_equal(packed, flat)


def test_rnn_interlayer_dropout_stochastic():
    """Two training forwards must use different inter-layer dropout masks."""
    T, B, I, H, L = 4, 3, 4, 8, 2
    n = rnn_ops.rnn_param_size(H, I, L, "lstm", False)
    rng = np.random.RandomState(0)
    args = [mx.nd.array(rng.randn(T, B, I).astype(np.float32)),
            mx.nd.array(rng.randn(n).astype(np.float32) * 0.3),
            mx.nd.array(np.zeros((L, B, H), np.float32)),
            mx.nd.array(np.zeros((L, B, H), np.float32))]
    with mx.autograd.train_mode():
        o1 = mx.nd.RNN(*args, state_size=H, num_layers=L, mode="lstm",
                       p=0.5).asnumpy()
        o2 = mx.nd.RNN(*args, state_size=H, num_layers=L, mode="lstm",
                       p=0.5).asnumpy()
    assert np.abs(o1 - o2).max() > 1e-6


def test_bidirectional_valid_length():
    """Reverse direction must not consume padding (regression: SequenceReverse
    handling in gluon BidirectionalCell.unroll)."""
    cell = gluon.rnn.BidirectionalCell(gluon.rnn.LSTMCell(4),
                                       gluon.rnn.LSTMCell(4))
    cell.initialize()
    rng = np.random.RandomState(0)
    x_valid = rng.randn(1, 3, 5).astype(np.float32)
    pad = np.full((1, 2, 5), 777.0, np.float32)  # poison padding
    x = np.concatenate([x_valid, pad], axis=1)
    vl = mx.nd.array([3.0])
    out, _ = cell.unroll(5, mx.nd.array(x), layout="NTC",
                         valid_length=vl, merge_outputs=True)
    out_short, _ = cell.unroll(3, mx.nd.array(x_valid), layout="NTC",
                               valid_length=mx.nd.array([3.0]),
                               merge_outputs=True)
    np.testing.assert_allclose(out.asnumpy()[:, :3], out_short.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    # padding positions masked to zero
    np.testing.assert_allclose(out.asnumpy()[:, 3:], 0.0, atol=1e-6)


def test_bucket_iter_empty_bucket():
    it = mx.rnn.BucketSentenceIter([[1, 2, 3, 4, 5]] * 20, batch_size=4,
                                   buckets=[2, 8])
    batches = list(it)
    assert all(b.bucket_key == 8 for b in batches)


def test_lstm_state_clip_per_timestep():
    T, B, I, H = 6, 2, 3, 4
    n = rnn_ops.rnn_param_size(H, I, 1, "lstm", False)
    rng = np.random.RandomState(0)
    big = mx.nd.array(rng.randn(n).astype(np.float32) * 3)
    x = mx.nd.array(rng.randn(T, B, I).astype(np.float32) * 3)
    z = mx.nd.array(np.zeros((1, B, H), np.float32))
    out, h, c = mx.nd.RNN(x, big, z, z, state_size=H, num_layers=1,
                          mode="lstm", state_outputs=True,
                          lstm_state_clip_min=-0.01, lstm_state_clip_max=0.01)
    assert np.abs(c.asnumpy()).max() <= 0.01 + 1e-7
    # outputs bounded by tanh(clip): per-timestep clipping affects them
    assert np.abs(out.asnumpy()).max() <= np.tanh(0.01) + 1e-6
