"""mxshard: whole-program static sharding propagation, multi-axis ring
formulas and the hardware-free ZeRO/tensor-parallel proof gate
(mxnet_tpu/analysis/shard_prop.py; docs/analysis.md "Sharding
propagation").

Golden fixtures cover the three canonical patterns — ZeRO-1 update
(reduce-scatter/all-gather), tensor-parallel matmul (inferred
partial-sum psum over ``model``), ring attention (scanned ppermute over
``sequence``) — and every new DST rule (006-010) has a broken-fixture
subprocess test proving exit code 2 with the rule named, plus the two
headline mutation kills: deleting the ZeRO all-gather fails the
STATIC_BUDGETS gate with DST007, inflating the optimizer state past
budget fails COST001.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

import jax
import jax.numpy as jnp
from jax import lax

import mxnet_tpu as mx

pytestmark = pytest.mark.analysis

from mxnet_tpu.analysis import cost as mxcost
from mxnet_tpu.analysis import shard_fixtures as sf
from mxnet_tpu.analysis import shard_prop as sp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules(findings):
    return sorted({f.rule_id for f in findings})


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-m", "mxnet_tpu.analysis"]
                          + list(args), capture_output=True, text=True,
                          cwd=REPO, env=env, timeout=300)


def _run_script(tmp_path, body):
    """Run a broken-fixture script in a subprocess; the script exits via
    ``exit_code(findings)`` so error-severity rules mean rc=2."""
    script = tmp_path / "fixture.py"
    script.write_text(textwrap.dedent("""\
        import os, sys
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax, jax.numpy as jnp
        from jax import lax
        from mxnet_tpu.analysis import exit_code
        from mxnet_tpu.analysis import shard_prop as sp
        """) + textwrap.dedent(body) + textwrap.dedent("""
        for f in findings:
            print(f)
        sys.exit(exit_code(findings))
        """))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    return subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=300)


# ---------------------------------------------------------------------------
# ShardSpec / MeshSpec basics
# ---------------------------------------------------------------------------
def test_shardspec_from_partition_spec():
    from jax.sharding import PartitionSpec as P
    mesh = sp.MeshSpec({"data": 8, "model": 4})
    s = sp.ShardSpec.from_partition_spec(P("data", None, ("model",)), 3)
    assert s.dims == (("data",), (), ("model",))
    assert s.axes() == {"data", "model"}
    assert s.shard_factor(mesh) == 32
    aval = jax.ShapeDtypeStruct((64, 2, 16), jnp.float32)
    assert s.local_bytes(aval, mesh) == 64 * 2 * 16 * 4 // 32
    assert sp.ShardSpec.from_partition_spec(None, 2).dims == ((), ())
    # a live Mesh is accepted as a MeshSpec source
    from mxnet_tpu.parallel import make_mesh
    m = sp.MeshSpec(make_mesh((4, 2), ("data", "model")))
    assert m.as_dict() == {"data": 4, "model": 2}


# ---------------------------------------------------------------------------
# golden fixture 1: the ZeRO-1 update (reduce-scatter / all-gather)
# ---------------------------------------------------------------------------
def test_zero1_golden_schedule_and_lint():
    k = 8
    mesh = sp.MeshSpec({"data": k})
    step, args = sf.zero1_step_program(k)
    closed = jax.make_jaxpr(step, axis_env=[("data", k)])(*args)
    report = sp.collective_schedule(closed, mesh)
    prims = [(e.prim, e.wire_bytes) for e in report.schedule]
    flat_bytes = sf.zero1_state_bytes(k)       # the padded flat vector
    rs = flat_bytes * (k - 1) // k
    # reduce_scatter (grads) + all_gather (new params) + loss pmean
    assert prims[0] == ("reduce_scatter", rs)
    assert prims[1] == ("all_gather", rs)
    assert prims[2][0] == "psum"
    # collective-byte parity with the replicated spelling: rs + ag ==
    # one ring all-reduce of the flat vector (2*(K-1)/K * bytes)
    assert prims[0][1] + prims[1][1] == \
        mxcost.collective_bytes("psum", flat_bytes, k)

    n_train = len(args[0])
    findings = sp.lint_sharded_step(
        closed, mesh, data_axes=("data",),
        varying_invars=[n_train + 1, n_train + 2],
        shard_dims={n_train: {0: ("data",)}},
        param_outvars=list(range(1, 1 + n_train)),
        param_names=["w1", "b1", "w2", "b2", "w3", "b3"])
    assert findings == []


def test_zero1_hbm_proof_via_budget_model():
    """The registered budget model proves the ZeRO-1 relation: modeled
    peak HBM at least optimizer-state x (1 - 1/8) below the replicated
    twin (the reduce-scatter spelling saves more — the post-reduction
    gradient buffer is 1/8-sized too)."""
    from mxnet_tpu.analysis.budget_models import build_model
    report, findings, shard = build_model("zero1_mlp_train_step")
    assert findings == []
    assert shard is not None
    ex = shard.extras
    assert ex["modeled_hbm_drop_bytes"] >= ex["zero1_floor_bytes"]
    assert ex["zero1_floor_bytes"] == \
        ex["optimizer_state_bytes"] * 7 // 8
    assert ex["zero1_peak_hbm_bytes"] == report.peak_hbm_bytes
    assert ex["replicated_twin_peak_hbm_bytes"] > report.peak_hbm_bytes
    assert 0 < ex["modeled_zero1_hbm_drop_pct"] < 100


# ---------------------------------------------------------------------------
# golden fixture 2: tensor-parallel matmul (inferred psum over model)
# ---------------------------------------------------------------------------
def test_tp_matmul_inferred_psum():
    fn, args, specs = sf.tp_matmul_program()
    mesh = sp.MeshSpec({"data": 8, "model": 4})
    closed = jax.make_jaxpr(fn)(*args)
    report = sp.propagate(closed, mesh, specs)
    assert report.reshards == []
    inferred = [e for e in report.schedule if e.inferred]
    assert len(inferred) == 1 and inferred[0].prim == "psum"
    assert inferred[0].axes == ("model",)
    # the partial output h @ W2 is (32, 32) f32 sharded over data on its
    # batch dim: local tile 4x32, one ring all-reduce over model (K=4)
    local = 32 * 32 * 4 // 8
    assert inferred[0].wire_bytes == \
        mxcost.collective_bytes("psum", local, 4)
    # output stays batch-sharded, partial resolved
    assert report.out_specs[0].dims[0] == ("data",)
    assert not report.out_specs[0].partial


def test_propagation_determinism():
    fn, args, specs = sf.tp_matmul_program()
    mesh = sp.MeshSpec({"data": 8, "model": 4})
    closed = jax.make_jaxpr(fn)(*args)
    a = sp.propagate(closed, mesh, specs).as_dict()
    b = sp.propagate(closed, mesh, specs).as_dict()
    assert a == b


# ---------------------------------------------------------------------------
# golden fixture 3: ring attention (scanned ppermute over sequence)
# ---------------------------------------------------------------------------
def test_ring_attention_schedule_matches_ring_formula():
    from mxnet_tpu.analysis.budget_models import build_model
    report, findings, shard = build_model("ring_attention_fwd")
    assert findings == []
    ex = shard.extras
    # 6 rotating buffers (fwd K/V + bwd K/V + dK/dV accumulators) x
    # K hops x chunk bytes — the closed-form ring formula
    assert ex["modeled_ring_attn_collective_bytes"] == \
        ex["ring_formula_bytes"] == 6 * ex["hops"] * ex["chunk_bytes"]
    assert report.collective_bytes == ex["ring_formula_bytes"]
    # every scheduled event is a ppermute over sequence, scaled K
    assert {e.prim for e in shard.schedule} == {"ppermute"}
    assert all(e.scale == ex["hops"] for e in shard.schedule)


def test_ulysses_all_to_all_priced():
    import importlib
    ra = importlib.import_module("mxnet_tpu.parallel.ring_attention")
    k = 4
    aval = jax.ShapeDtypeStruct((2, 16, 8, 16), jnp.float32)
    closed = jax.make_jaxpr(
        lambda q, kk, v: ra.ulysses_attention(q, kk, v, "sequence"),
        axis_env=[("sequence", k)])(aval, aval, aval)
    report = sp.collective_schedule(closed, sp.MeshSpec({"sequence": k}))
    a2a = [e for e in report.schedule if e.prim == "all_to_all"]
    assert len(a2a) == 4          # q/k/v in, output back
    payload = 2 * 16 * 8 * 16 * 4
    assert all(e.wire_bytes ==
               mxcost.collective_bytes("all_to_all", payload, k)
               for e in a2a)


# ---------------------------------------------------------------------------
# global view faithfulness: trainer inferred == replica explicit
# ---------------------------------------------------------------------------
def _mlp_trainer():
    from mxnet_tpu import gluon
    from mxnet_tpu.analysis.budget_models import _cpu_mesh
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import DataParallelTrainer
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx.init.Xavier())
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=_cpu_mesh())


def test_trainer_shard_report_matches_replica_spelling():
    """The GSPMD story, proven both ways: the global-view propagation
    over the full-batch step (no explicit collectives anywhere) must
    INFER gradient psums whose total bytes equal the per-replica
    spelling's explicit pmean bytes exactly."""
    tr = _mlp_trainer()
    srep = tr.shard_report(data_shape=(64, 16), label_shape=(64,),
                           declared_axis_size=8)
    assert srep.reshards == []
    assert all(e.inferred for e in srep.schedule)
    crep = tr.cost_report(data_shape=(64, 16), label_shape=(64,),
                          declared_axis_size=8)
    assert srep.collective_bytes_per_axis == \
        crep.collective_bytes_per_axis
    assert srep.collective_bytes_per_axis["data"] > 0


def test_symbol_shard_report_tensor_parallel():
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu import symbol as sym
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=64, name="tp_fc1")
    a = sym.Activation(h, act_type="relu", name="tp_relu")
    out = sym.FullyConnected(a, num_hidden=16, name="tp_fc2")
    # the Megatron pairing on (out, in) FC weights: fc1 column-parallel
    # (out dim over model -> the activation comes out model-sharded),
    # fc2 row-parallel (in dim over model -> the contraction meets the
    # sharded activation and the output is a partial-sum over model
    # that the propagation must resolve with an inferred psum)
    specs = {"tp_fc1_weight": P("model", None),
             "tp_fc2_weight": P(None, "model")}
    rep = out.shard_report(shapes={"data": (8, 64)},
                           mesh_axes={"data": 8, "model": 4},
                           in_specs=specs)
    assert rep is not None
    inferred = [e for e in rep.schedule
                if e.inferred and "model" in e.axes]
    assert inferred, rep.as_dict()


# ---------------------------------------------------------------------------
# broken fixtures: one rc=2 subprocess per new DST rule, rule named
# ---------------------------------------------------------------------------
def test_dst006_wrong_axis_grad_reduction_rc2(tmp_path):
    proc = _run_script(tmp_path, """
        def bad(w, x):
            g = jax.grad(lambda w: (x @ w).sum())(w)
            return w - 0.1 * lax.pmean(g, "model")   # wrong axis
        closed = jax.make_jaxpr(
            bad, axis_env=[("data", 8), ("model", 4)])(
            jax.ShapeDtypeStruct((16, 4), jnp.float32),
            jax.ShapeDtypeStruct((8, 16), jnp.float32))
        findings = sp.lint_sharded_step(
            closed, sp.MeshSpec({"data": 8, "model": 4}),
            varying_invars=[1], param_outvars=[0], param_names=["w"])
    """)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST006" in proc.stdout


def test_dst006_model_sharded_param_reduced_over_model_rc2(tmp_path):
    proc = _run_script(tmp_path, """
        def bad(w_sh, x):
            g = jax.grad(lambda w: (x @ w).sum())(w_sh)
            # params are model-sharded: reducing over data x model
            # mixes unrelated shard coordinates
            return w_sh - 0.1 * lax.psum(g, ("data", "model"))
        closed = jax.make_jaxpr(
            bad, axis_env=[("data", 8), ("model", 4)])(
            jax.ShapeDtypeStruct((16, 4), jnp.float32),
            jax.ShapeDtypeStruct((8, 16), jnp.float32))
        findings = sp.lint_sharded_step(
            closed, sp.MeshSpec({"data": 8, "model": 4}),
            varying_invars=[1], shard_dims={0: {1: ("model",)}},
            param_outvars=[0], param_names=["w"])
    """)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST006" in proc.stdout


def test_dst007_missing_all_gather_fails_budget_gate_rc2(tmp_path):
    """Headline mutation kill #1: deleting the all-gather from the ZeRO
    fixture fails the STATIC_BUDGETS gate with DST007 named."""
    script = tmp_path / "mutate.py"
    script.write_text(
        "import os, sys\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu.analysis import shard_fixtures\n"
        "shard_fixtures.ZERO1_ALL_GATHER = False\n"
        "from mxnet_tpu.analysis.__main__ import main\n"
        "sys.exit(main(['--cost', '--budget', %r]))\n"
        % os.path.join(REPO, "STATIC_BUDGETS.json"))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=300)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST007" in proc.stdout
    assert "all_gather" in proc.stdout


def test_cost001_unsharded_optimizer_state_fails_budget_gate_rc2(
        tmp_path):
    """Headline mutation kill #2: inflating the ZeRO step's optimizer
    state back to replicated blows the pinned peak-HBM budget (and the
    ZeRO-1 relation check) — COST001, exit 2."""
    script = tmp_path / "mutate.py"
    script.write_text(
        "import os, sys\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu.analysis import shard_fixtures\n"
        "shard_fixtures.ZERO1_SHARD_STATE = False\n"
        "from mxnet_tpu.analysis.__main__ import main\n"
        "sys.exit(main(['--cost', '--budget', %r]))\n"
        % os.path.join(REPO, "STATIC_BUDGETS.json"))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=300)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "COST001" in proc.stdout
    assert "zero1_mlp_train_step" in proc.stdout


def test_dst008_overlapping_subaxis_reduction_rc2(tmp_path):
    proc = _run_script(tmp_path, """
        def bad(w, x):
            g = jax.grad(lambda w: (x @ w).sum())(w)
            g = lax.psum(g, "data")
            g = lax.psum(g, ("data", "model"))   # overlaps the first
            return w - 0.1 * g
        closed = jax.make_jaxpr(
            bad, axis_env=[("data", 8), ("model", 4)])(
            jax.ShapeDtypeStruct((16, 4), jnp.float32),
            jax.ShapeDtypeStruct((8, 16), jnp.float32))
        findings = sp.lint_sharded_step(
            closed, sp.MeshSpec({"data": 8, "model": 4}),
            varying_invars=[1], param_outvars=[0], param_names=["w"])
    """)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST008" in proc.stdout


def test_dst009_broken_ring_rc2(tmp_path):
    proc = _run_script(tmp_path, """
        K = 8
        def bad(x):
            perm = [(i, (i + 1) % K) for i in range(K)]
            def hop(c, _):
                return lax.ppermute(c, "sequence", perm), ()
            out, _ = lax.scan(hop, x, jnp.arange(K - 1))  # a hop short
            return out
        closed = jax.make_jaxpr(bad, axis_env=[("sequence", K)])(
            jax.ShapeDtypeStruct((8, 8), jnp.float32))
        findings = sp.lint_ring_schedule(closed, "sequence", K)
    """)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST009" in proc.stdout
    assert "ring formula" in proc.stdout


def test_dst009_partial_perm_rc2(tmp_path):
    proc = _run_script(tmp_path, """
        K = 8
        def bad(x):
            perm = [(i, (i + 1) % K) for i in range(K - 1)]  # no ring
            def hop(c, _):
                return lax.ppermute(c, "sequence", perm), ()
            out, _ = lax.scan(hop, x, jnp.arange(K))
            return out
        closed = jax.make_jaxpr(bad, axis_env=[("sequence", K)])(
            jax.ShapeDtypeStruct((8, 8), jnp.float32))
        findings = sp.lint_ring_schedule(closed, "sequence", K)
    """)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST009" in proc.stdout


def test_dst010_hidden_reshard_rc2(tmp_path):
    proc = _run_script(tmp_path, """
        from jax.sharding import PartitionSpec as P
        closed = jax.make_jaxpr(lambda a, b: a + b)(
            jax.ShapeDtypeStruct((16, 16), jnp.float32),
            jax.ShapeDtypeStruct((16, 16), jnp.float32))
        findings, report = sp.lint_global_sharding(
            closed, sp.MeshSpec({"data": 8, "model": 4}),
            [P("model", None), P(None, "model")])
        assert report.reshards, "expected a forced reshard"
    """)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST010" in proc.stdout
    assert "all_to_all" in proc.stdout


# ---------------------------------------------------------------------------
# COST004: unpriced collectives are named, never silent
# ---------------------------------------------------------------------------
def test_cost004_undeclared_axis_is_named():
    closed = jax.make_jaxpr(
        lambda x: lax.ppermute(x, "sequence", [(0, 1), (1, 0)]),
        axis_env=[("sequence", 2)])(jnp.zeros((1024,)))
    # analyzed WITHOUT the axis declared: the ppermute would price at
    # zero — the report must name it and COST004 must fire
    report = mxcost.analyze_jaxpr(closed)
    assert report.collective_bytes == 0
    rows = report.as_dict()["unpriced_collectives"]
    assert rows == [{"prim": "ppermute", "axis": "sequence",
                     "reason": "axis size undeclared"}]
    findings = mxcost.unpriced_findings(report, subject="t")
    assert rules(findings) == ["COST004"]
    # declared: priced, nothing unpriced
    priced = mxcost.analyze_jaxpr(closed, axis_sizes={"sequence": 2})
    assert priced.collective_bytes == 1024 * 4
    assert priced.as_dict()["unpriced_collectives"] == []


def test_cost004_axis_local_primitives_not_flagged():
    closed = jax.make_jaxpr(
        lambda x: x + lax.axis_index("data"),
        axis_env=[("data", 8)])(jnp.zeros((4,), jnp.int32))
    report = mxcost.analyze_jaxpr(closed)
    assert report.as_dict()["unpriced_collectives"] == []


def test_psum_of_literal_is_axis_arithmetic_not_collective():
    """lax.psum(1, axis) — the axis-size idiom all over ring attention
    — must neither price as a collective nor trip the dead-reduction
    rule."""
    k = 8
    closed = jax.make_jaxpr(
        lambda x: x * lax.psum(1, "sequence"),
        axis_env=[("sequence", k)])(jnp.zeros((4,), jnp.int32))
    report = sp.collective_schedule(closed, sp.MeshSpec({"sequence": k}))
    assert report.schedule == []
    findings = sp.lint_sharded_step(
        closed, sp.MeshSpec({"sequence": k}), data_axes=("sequence",),
        varying_invars=[0], param_outvars=[])
    assert findings == []


# ---------------------------------------------------------------------------
# CLI / schema / tooling wiring
# ---------------------------------------------------------------------------
def test_shard_cli_json_section():
    proc = _run_cli("--cost", "--shard", "--json", "--model",
                    "zero1_mlp_train_step,ring_attention_fwd")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema_version"] == 6
    shard = payload["shard"]
    assert shard["rules"] == ["DST006", "DST007", "DST008", "DST009",
                              "DST010", "DST011", "DST012", "COST004"]
    z = shard["reports"]["zero1_mlp_train_step"]
    assert z["mesh"] == {"data": 8}
    assert [e["prim"] for e in z["schedule"]][:2] == \
        ["reduce_scatter", "all_gather"]
    assert z["extras"]["modeled_zero1_hbm_drop_pct"] > 0
    r = shard["reports"]["ring_attention_fwd"]
    assert r["extras"]["modeled_ring_attn_collective_bytes"] == \
        r["extras"]["ring_formula_bytes"]
    # without --shard the section is absent (pre-3 consumers unaffected)
    proc = _run_cli("--cost", "--json", "--model", "mlp_infer")
    assert "shard" not in json.loads(proc.stdout)


def test_parse_log_reads_and_refuses_analysis_schema(tmp_path):
    """tools/parse_log.py understands the v3 analysis JSON and refuses
    newer schema_versions (the regression twin of the telemetry-JSON
    refusal test in test_telemetry.py)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    doc = {"version": 1, "schema_version": 3, "findings": [
        {"rule": "DST007", "severity": "error", "subject": "w1",
         "message": "m"}],
        "cost": {"m": {"flops": 10, "collective_bytes": 3}},
        "shard": {"reports": {"m": {"collective_bytes": 3,
                                    "n_collectives": 1,
                                    "extras": {"x": 2.5}}}}}
    rows = parse_log.parse_analysis_json(doc)
    names = [n for n, _ in rows]
    assert 'finding.DST007{subject="w1"}' in names
    assert "cost.m.flops" in names and "shard.m.x" in names
    # v6 (the mxgen codegen section) is understood...
    parse_log.parse_analysis_json(dict(doc, schema_version=6))
    with pytest.raises(ValueError, match="newer"):
        parse_log.parse_analysis_json(dict(doc, schema_version=99))
    # end to end through the CLI: a v7 document is refused (rc != 0)
    newer = tmp_path / "newer.json"
    newer.write_text(json.dumps(dict(doc, schema_version=7)))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         str(newer)], capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0
    assert "newer" in (proc.stderr + proc.stdout)


def test_bench_compare_gates_modeled_shard_metrics(tmp_path):
    """The two static_cost keys gate from their first two live rounds:
    a shrinking ZeRO drop (higher-direction) and growing ring bytes
    (lower_rel) both regress."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_compare
    finally:
        sys.path.pop(0)

    def rec(n, **parsed):
        p = tmp_path / ("BENCH_r%02d.json" % n)
        p.write_text(json.dumps({"n": n, "cmd": "bench", "rc": 0,
                                 "tail": "", "parsed": parsed}))
        return str(p)

    ok = [rec(6, modeled_zero1_hbm_drop_pct=31.3,
              modeled_ring_attn_collective_bytes=3145728),
          rec(7, modeled_zero1_hbm_drop_pct=31.3,
              modeled_ring_attn_collective_bytes=3145728)]
    report = bench_compare.compare(ok)
    assert report["regressions"] == []
    assert report["gates"]["modeled_ring_attn_collective_bytes"][
        "verdict"] == "ok"

    bad = ok + [rec(8, modeled_zero1_hbm_drop_pct=20.0,
                    modeled_ring_attn_collective_bytes=4000000)]
    report = bench_compare.compare(bad)
    assert set(report["regressions"]) == {
        "modeled_zero1_hbm_drop_pct",
        "modeled_ring_attn_collective_bytes"}


def test_shard_self_check_sweeps_clean():
    """What --self-check runs: golden mini-fixtures + the shipped
    ring/Ulysses paths lint clean under the new rules (currently with
    zero inline disables)."""
    from mxnet_tpu.analysis import lint_parallel_sources, shard_self_check
    assert shard_self_check() == []
    assert lint_parallel_sources() == []
