"""conv3x3_epilogue (implicit-GEMM Pallas conv) vs the XLA conv oracle,
interpret mode on CPU (reference equivalence:
src/operator/quantization/quantized_conv.cu — implicit-GEMM int8 conv
with fused requantize; the bf16 variant folds inference BN + relu)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from mxnet_tpu.ops.pallas_kernels import conv3x3_epilogue


def _oracle(x, w, scale, shift, relu, out_int8):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    acc = lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)], dimension_numbers=dn,
        preferred_element_type=jnp.int32 if out_int8 else jnp.float32)
    real = np.asarray(acc).astype(np.float32) * scale + shift
    if relu:
        real = np.maximum(real, 0.0)
    if out_int8:
        return np.clip(np.round(real), -127, 127).astype(np.int8)
    return real


@pytest.mark.parametrize("shape", [(2, 8, 8, 16), (4, 6, 6, 16),
                                   (1, 14, 14, 8)])
@pytest.mark.parametrize("relu", [True, False])
def test_int8_exact_vs_xla(shape, relu):
    """int8 path is BIT-exact vs XLA's s8xs8->s32 conv + requantize."""
    rng = np.random.RandomState(0)
    N, H, W, C = shape
    x = jnp.asarray(rng.randint(-127, 128, shape), jnp.int8)
    w = jnp.asarray(rng.randint(-16, 16, (3, 3, C, 2 * C)), jnp.int8)
    scale = (rng.rand(2 * C) * 0.01 + 1e-3).astype(np.float32)
    shift = rng.randn(2 * C).astype(np.float32)
    out = conv3x3_epilogue(x, w, scale, shift, relu=relu)
    ref = _oracle(x, w, scale, shift, relu, out_int8=True)
    assert out.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_bf16_close_vs_xla():
    """bf16 path (fused BN-scale/shift + relu) within bf16 rounding."""
    rng = np.random.RandomState(1)
    x32 = rng.randn(2, 8, 8, 16).astype(np.float32)
    w32 = (rng.randn(3, 3, 16, 32) * 0.1).astype(np.float32)
    scale = (rng.rand(32) + 0.5).astype(np.float32)
    shift = rng.randn(32).astype(np.float32)
    x = jnp.asarray(x32, jnp.bfloat16)
    w = jnp.asarray(w32, jnp.bfloat16)
    out = conv3x3_epilogue(x, w, scale, shift, relu=True)
    ref = _oracle(x.astype(jnp.float32), w.astype(jnp.float32),
                  scale, shift, relu=True, out_int8=False)
    assert out.dtype == jnp.bfloat16
    got = np.asarray(out).astype(np.float32)
    assert np.max(np.abs(got - ref)) < 0.05 * max(1.0, np.abs(ref).max())


def test_padded_cout_slice():
    """Cout below the 128-lane tile comes back exactly (zero-pad + slice
    round trip: the tn-lane padding never leaks into the result)."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randint(-5, 5, (2, 6, 6, 8)), jnp.int8)
    w = jnp.asarray(rng.randint(-4, 4, (3, 3, 8, 24)), jnp.int8)
    scale = np.full(24, 0.02, np.float32)
    shift = np.zeros(24, np.float32)
    out = conv3x3_epilogue(x, w, scale, shift, relu=False)
    assert out.shape == (2, 6, 6, 24)
    ref = _oracle(x, w, scale, shift, relu=False, out_int8=True)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_vmem_budget_clamp_auto():
    """Auto tile heuristic shrinks nb/th to the VMEM byte budget at large
    Cin instead of handing Mosaic an oversized scratch (ADVICE r4):
    Cin=512 bf16 at 28x28 would be ~13MB of col scratch with the H/W-only
    sizing; the clamped call must still run and match the oracle."""
    rng = np.random.RandomState(0)
    N, H, W, C = 2, 28, 28, 512
    x = jnp.asarray(rng.randn(N, H, W, C), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, C, 128) * 0.05, jnp.float32)
    scale = np.ones(128, np.float32)
    shift = np.zeros(128, np.float32)
    out = conv3x3_epilogue(x, w, scale, shift, relu=False)
    ref = _oracle(x, w, scale, shift, relu=False, out_int8=False)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               rtol=2e-2, atol=2e-2)


def test_vmem_budget_explicit_tiles_fail_loudly():
    """Explicit nb/th that cannot fit the budget raise with the byte
    arithmetic in the message, not at Mosaic compile time."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 32, 32, 512), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 512, 128) * 0.05, jnp.float32)
    ones, zeros = np.ones(128, np.float32), np.zeros(128, np.float32)
    with pytest.raises(ValueError, match="VMEM"):
        conv3x3_epilogue(x, w, ones, zeros, nb=8, th=32)
