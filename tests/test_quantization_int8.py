"""int8 quantization beyond FC: conv + pooling (VERDICT r1 item 5).

Reference: src/operator/quantization/quantized_conv.cu,
quantized_pooling.cc, quantize_graph_pass.cc.
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _quantize_int8(x):
    amax = np.abs(x).max()
    q = np.clip(np.round(x * 127.0 / amax), -127, 127).astype(np.int8)
    return q, amax


class TestQuantizedConvOp:
    def test_matches_fp32_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        qx, xa = _quantize_int8(x)
        qw, wa = _quantize_int8(w)
        acc, mn, mx_ = nd.contrib.quantized_conv(
            nd.array(qx, dtype=np.int8), nd.array(qw, dtype=np.int8),
            nd.array([-xa]), nd.array([xa]),
            nd.array([-wa]), nd.array([wa]),
            kernel=(3, 3), num_filter=4, pad=(1, 1))
        out = nd.contrib.dequantize(acc, mn, mx_).asnumpy()
        ref = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                             num_filter=4, pad=(1, 1),
                             no_bias=True).asnumpy()
        # int8 quantization error bound: relative to the output scale
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.05, err

    def test_bias_and_stride(self):
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 9, 9).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        b = rng.randn(3).astype(np.float32)
        qx, xa = _quantize_int8(x)
        qw, wa = _quantize_int8(w)
        qb, ba = _quantize_int8(b)
        acc, mn, mx_ = nd.contrib.quantized_conv(
            nd.array(qx, dtype=np.int8), nd.array(qw, dtype=np.int8),
            nd.array([-xa]), nd.array([xa]),
            nd.array([-wa]), nd.array([wa]),
            nd.array(qb, dtype=np.int8), nd.array([-ba]), nd.array([ba]),
            kernel=(3, 3), num_filter=3, stride=(2, 2), pad=(1, 1),
            no_bias=False)
        out = nd.contrib.dequantize(acc, mn, mx_).asnumpy()
        ref = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                             kernel=(3, 3), num_filter=3, stride=(2, 2),
                             pad=(1, 1)).asnumpy()
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.05, err


class TestQuantizedPoolingOp:
    @pytest.mark.parametrize("pool_type", ["max", "avg"])
    def test_matches_fp32_pooling(self, pool_type):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        qx, xa = _quantize_int8(x)
        out, mn, mx_ = nd.contrib.quantized_pooling(
            nd.array(qx, dtype=np.int8), nd.array([-xa]), nd.array([xa]),
            kernel=(2, 2), stride=(2, 2), pool_type=pool_type)
        assert out.dtype == np.int8
        deq = nd.contrib.dequantize(out, mn, mx_).asnumpy()
        ref = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                         pool_type=pool_type).asnumpy()
        err = np.abs(deq - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.05, err

    def test_global_avg(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 2, 6, 6).astype(np.float32)
        qx, xa = _quantize_int8(x)
        out, mn, mx_ = nd.contrib.quantized_pooling(
            nd.array(qx, dtype=np.int8), nd.array([-xa]), nd.array([xa]),
            global_pool=True, pool_type="avg")
        deq = nd.contrib.dequantize(out, mn, mx_).asnumpy()
        ref = x.mean(axis=(2, 3), keepdims=True)
        assert np.abs(deq - ref).max() < 0.05 * np.abs(x).max()


def test_quantize_model_rewrites_conv_and_pooling():
    """The graph pass covers conv + pooling, not just FC."""
    rng = np.random.RandomState(4)
    X = rng.randn(64, 3, 16, 16).astype(np.float32)
    y = (np.arange(64) % 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, 16)
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                            name="conv1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                        name="pool1")
    fc = mx.sym.FullyConnected(p1, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params(initializer=mx.init.Xavier())
    arg, aux = mod.get_params()
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=it, num_calib_examples=64)
    ops = [n["op"] for n in json.loads(qsym.tojson())["nodes"]]
    assert "_contrib_quantized_conv" in ops
    assert "_contrib_quantized_pooling" in ops
    assert "_contrib_quantized_fully_connected" in ops
    # int8 graph outputs close to fp32
    qmod = mx.mod.Module(qsym)
    qmod.bind(it.provide_data, it.provide_label, for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    qmod.forward(batch, is_train=False)
    ref = mod.get_outputs()[0].asnumpy()
    out = qmod.get_outputs()[0].asnumpy()
    agree = (ref.argmax(1) == out.argmax(1)).mean()
    assert agree >= 0.9, agree


def test_resnet18_int8_prediction_agreement():
    """Symbolic resnet-18 (thumbnail): int8 argmax agreement with fp32 —
    the VERDICT's 'accuracy within 1%' check, done as prediction agreement
    since weights are random-initialized."""
    from mxnet_tpu.symbol.models import resnet_symbol
    rng = np.random.RandomState(5)
    X = rng.rand(64, 3, 32, 32).astype(np.float32)
    y = (np.arange(64) % 10).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, 16)
    net = resnet_symbol(18, num_classes=10, thumbnail=True)
    mod = mx.mod.Module(net)
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params(initializer=mx.init.Xavier())
    arg, aux = mod.get_params()
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=it, num_calib_examples=64,
        excluded_sym_names=["stem_conv"])
    ops = [n["op"] for n in json.loads(qsym.tojson())["nodes"]]
    assert "_contrib_quantized_conv" in ops
    qmod = mx.mod.Module(qsym)
    qmod.bind(it.provide_data, it.provide_label, for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    it.reset()
    agree = n_tot = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        qmod.forward(batch, is_train=False)
        ref = mod.get_outputs()[0].asnumpy().argmax(1)
        out = qmod.get_outputs()[0].asnumpy().argmax(1)
        agree += (ref == out).sum()
        n_tot += len(ref)
    assert agree / n_tot >= 0.95, agree / n_tot
