"""int8 quantization beyond FC: conv + pooling (VERDICT r1 item 5).

Reference: src/operator/quantization/quantized_conv.cu,
quantized_pooling.cc, quantize_graph_pass.cc.
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _quantize_int8(x):
    amax = np.abs(x).max()
    q = np.clip(np.round(x * 127.0 / amax), -127, 127).astype(np.int8)
    return q, amax


class TestQuantizedConvOp:
    def test_matches_fp32_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        qx, xa = _quantize_int8(x)
        qw, wa = _quantize_int8(w)
        acc, mn, mx_ = nd.contrib.quantized_conv(
            nd.array(qx, dtype=np.int8), nd.array(qw, dtype=np.int8),
            nd.array([-xa]), nd.array([xa]),
            nd.array([-wa]), nd.array([wa]),
            kernel=(3, 3), num_filter=4, pad=(1, 1))
        out = nd.contrib.dequantize(acc, mn, mx_).asnumpy()
        ref = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                             num_filter=4, pad=(1, 1),
                             no_bias=True).asnumpy()
        # int8 quantization error bound: relative to the output scale
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.05, err

    def test_bias_and_stride(self):
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 9, 9).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        b = rng.randn(3).astype(np.float32)
        qx, xa = _quantize_int8(x)
        qw, wa = _quantize_int8(w)
        qb, ba = _quantize_int8(b)
        acc, mn, mx_ = nd.contrib.quantized_conv(
            nd.array(qx, dtype=np.int8), nd.array(qw, dtype=np.int8),
            nd.array([-xa]), nd.array([xa]),
            nd.array([-wa]), nd.array([wa]),
            nd.array(qb, dtype=np.int8), nd.array([-ba]), nd.array([ba]),
            kernel=(3, 3), num_filter=3, stride=(2, 2), pad=(1, 1),
            no_bias=False)
        out = nd.contrib.dequantize(acc, mn, mx_).asnumpy()
        ref = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                             kernel=(3, 3), num_filter=3, stride=(2, 2),
                             pad=(1, 1)).asnumpy()
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.05, err


class TestQuantizedPoolingOp:
    @pytest.mark.parametrize("pool_type", ["max", "avg"])
    def test_matches_fp32_pooling(self, pool_type):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        qx, xa = _quantize_int8(x)
        out, mn, mx_ = nd.contrib.quantized_pooling(
            nd.array(qx, dtype=np.int8), nd.array([-xa]), nd.array([xa]),
            kernel=(2, 2), stride=(2, 2), pool_type=pool_type)
        assert out.dtype == np.int8
        deq = nd.contrib.dequantize(out, mn, mx_).asnumpy()
        ref = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                         pool_type=pool_type).asnumpy()
        err = np.abs(deq - ref).max() / (np.abs(ref).max() + 1e-6)
        assert err < 0.05, err

    def test_global_avg(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 2, 6, 6).astype(np.float32)
        qx, xa = _quantize_int8(x)
        out, mn, mx_ = nd.contrib.quantized_pooling(
            nd.array(qx, dtype=np.int8), nd.array([-xa]), nd.array([xa]),
            global_pool=True, pool_type="avg")
        deq = nd.contrib.dequantize(out, mn, mx_).asnumpy()
        ref = x.mean(axis=(2, 3), keepdims=True)
        assert np.abs(deq - ref).max() < 0.05 * np.abs(x).max()


def test_quantize_model_rewrites_conv_and_pooling():
    """The graph pass covers conv + pooling, not just FC."""
    rng = np.random.RandomState(4)
    X = rng.randn(64, 3, 16, 16).astype(np.float32)
    y = (np.arange(64) % 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, 16)
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                            name="conv1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                        name="pool1")
    fc = mx.sym.FullyConnected(p1, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params(initializer=mx.init.Xavier())
    arg, aux = mod.get_params()
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=it, num_calib_examples=64)
    ops = [n["op"] for n in json.loads(qsym.tojson())["nodes"]]
    assert "_contrib_quantized_conv" in ops
    assert "_contrib_quantized_pooling" in ops
    assert "_contrib_quantized_fully_connected" in ops
    # int8 graph outputs close to fp32
    qmod = mx.mod.Module(qsym)
    qmod.bind(it.provide_data, it.provide_label, for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    qmod.forward(batch, is_train=False)
    ref = mod.get_outputs()[0].asnumpy()
    out = qmod.get_outputs()[0].asnumpy()
    agree = (ref.argmax(1) == out.argmax(1)).mean()
    assert agree >= 0.9, agree


@pytest.mark.slow
def test_resnet18_int8_prediction_agreement():
    """Symbolic resnet-18 (thumbnail): int8 argmax agreement with fp32 —
    the VERDICT's 'accuracy within 1%' check, done as prediction agreement
    since weights are random-initialized."""
    from mxnet_tpu.symbol.models import resnet_symbol
    rng = np.random.RandomState(5)
    X = rng.rand(64, 3, 32, 32).astype(np.float32)
    y = (np.arange(64) % 10).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, 16)
    net = resnet_symbol(18, num_classes=10, thumbnail=True)
    mod = mx.mod.Module(net)
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params(initializer=mx.init.Xavier())
    arg, aux = mod.get_params()
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=it, num_calib_examples=64,
        excluded_sym_names=["stem_conv"])
    ops = [n["op"] for n in json.loads(qsym.tojson())["nodes"]]
    assert "_contrib_quantized_conv" in ops
    qmod = mx.mod.Module(qsym)
    qmod.bind(it.provide_data, it.provide_label, for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    it.reset()
    agree = n_tot = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        qmod.forward(batch, is_train=False)
        ref = mod.get_outputs()[0].asnumpy().argmax(1)
        out = qmod.get_outputs()[0].asnumpy().argmax(1)
        agree += (ref == out).sum()
        n_tot += len(ref)
    assert agree / n_tot >= 0.95, agree / n_tot


# ---------------------------------------------------------------------------
# Round 3: entropy/KL calibration, BN folding, NHWC int8 graphs
# (reference: contrib/quantization.py:253 _get_optimal_threshold)
# ---------------------------------------------------------------------------
class TestKLCalibration:
    def test_clips_outliers(self):
        """A gaussian bulk with far outliers: the KL threshold must clip
        the outliers instead of stretching the int8 range over them."""
        from mxnet_tpu.contrib.quantization import optimal_threshold
        rng = np.random.RandomState(0)
        a = np.concatenate([rng.randn(100000), [50.0, -60.0]])
        amax = np.abs(a).max()
        edges = np.linspace(-amax, amax, 8002)
        hist, _ = np.histogram(a, bins=edges)
        th = optimal_threshold(hist, edges)
        assert 2.0 < th < 15.0, th

    def test_keeps_full_range_without_outliers(self):
        from mxnet_tpu.contrib.quantization import optimal_threshold
        rng = np.random.RandomState(1)
        b = rng.uniform(-1, 1, 100000)
        edges = np.linspace(-1, 1, 8002)
        hist, _ = np.histogram(b, bins=edges)
        th = optimal_threshold(hist, edges)
        assert th > 0.9, th

    def test_entropy_beats_naive_on_bulk(self):
        """On outlier-heavy data the KL threshold trades one clipped
        outlier for far higher fidelity on the bulk of the distribution —
        naive min/max squeezes the gaussian bulk into a handful of int8
        levels."""
        from mxnet_tpu.contrib.quantization import optimal_threshold
        rng = np.random.RandomState(2)
        bulk = rng.randn(50000).astype(np.float32)
        a = np.concatenate([bulk, [80.0]])
        amax = np.abs(a).max()
        edges = np.linspace(-amax, amax, 8002)
        hist, _ = np.histogram(a, bins=edges)
        th = optimal_threshold(hist, edges)

        def bulk_sqnr(t):
            q = np.clip(np.round(bulk / t * 127), -127, 127) * t / 127
            return 10 * np.log10(
                (bulk ** 2).sum() / ((bulk - q) ** 2).sum())

        assert bulk_sqnr(th) > bulk_sqnr(amax) + 15.0  # >15 dB better


class TestBNFolding:
    def _toy(self, layout):
        rng = np.random.RandomState(3)
        shape = (4, 3, 16, 16) if layout == "NCHW" else (4, 16, 16, 3)
        X = rng.rand(*shape).astype(np.float32)
        it = mx.io.NDArrayIter(X, np.zeros(4, np.float32), 4)
        data = mx.sym.Variable("data")
        c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                               pad=(1, 1), no_bias=True, layout=layout,
                               name="c1")
        bn = mx.sym.BatchNorm(c, fix_gamma=False, name="bn1",
                              axis=3 if layout == "NHWC" else 1)
        r = mx.sym.Activation(bn, act_type="relu")
        fc = mx.sym.FullyConnected(r, num_hidden=4, name="fc")
        net = mx.sym.SoftmaxOutput(fc, name="softmax")
        mod = mx.mod.Module(net)
        mod.bind(it.provide_data, it.provide_label, for_training=False)
        mod.init_params(initializer=mx.init.Xavier())
        arg, aux = mod.get_params()
        # non-trivial moving stats so folding actually does arithmetic
        for k in list(aux):
            a = aux[k].asnumpy()
            aux[k] = mx.nd.array(
                rng.rand(*a.shape).astype(np.float32) * 0.5 +
                (1.0 if k.endswith("_var") else -0.2))
        mod.set_params(arg, aux)
        return net, mod, arg, aux, it

    @pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
    def test_fold_exact(self, layout):
        from mxnet_tpu.contrib.quantization import fold_batch_norms
        net, mod, arg, aux, it = self._toy(layout)
        it.reset()
        b = next(iter(it))
        mod.forward(b, is_train=False)
        ref = mod.get_outputs()[0].asnumpy()
        fsym, farg, faux = fold_batch_norms(net, arg, aux)
        ops = [n.op for n in fsym._nodes()]
        assert "BatchNorm" not in ops
        fmod = mx.mod.Module(fsym)
        fmod.bind(it.provide_data, it.provide_label, for_training=False)
        fmod.init_params(arg_params=farg, aux_params=faux)
        fmod.forward(b, is_train=False)
        out = fmod.get_outputs()[0].asnumpy()
        assert np.abs(ref - out).max() < 1e-4

    def test_fold_skips_shared_conv(self):
        """A conv consumed by two heads must not be folded."""
        from mxnet_tpu.contrib.quantization import fold_batch_norms
        data = mx.sym.Variable("data")
        c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                               pad=(1, 1), no_bias=True, name="c1")
        bn = mx.sym.BatchNorm(c, name="bn1")
        out = mx.sym.Group([bn, c])
        arg = {"c1_weight": mx.nd.array(np.ones((4, 3, 3, 3), np.float32))}
        aux = {"bn1_moving_mean": mx.nd.array(np.zeros(4, np.float32)),
               "bn1_moving_var": mx.nd.array(np.ones(4, np.float32))}
        fsym, _, _ = fold_batch_norms(out, arg, aux)
        assert "BatchNorm" in [n.op for n in fsym._nodes()]


@pytest.mark.slow
def test_quantize_model_entropy_nhwc_resnet():
    """End to end: NHWC resnet-18, entropy calibration, BN folding — the
    round-3 int8 path (quantize_v2 ranges come from KL thresholds)."""
    from mxnet_tpu.symbol.models import resnet_symbol
    rng = np.random.RandomState(6)
    X = rng.rand(32, 32, 32, 3).astype(np.float32)
    y = (np.arange(32) % 10).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, 16)
    net = resnet_symbol(18, num_classes=10, thumbnail=True, layout="NHWC")
    mod = mx.mod.Module(net)
    mod.bind(it.provide_data, it.provide_label, for_training=False)
    mod.init_params(initializer=mx.init.Xavier())
    arg, aux = mod.get_params()
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=it, num_calib_examples=32,
        calib_mode="entropy", excluded_sym_names=["stem_conv"])
    ops = [n.op for n in qsym._nodes()]
    assert "_contrib_quantized_conv" in ops
    assert "BatchNorm" not in ops  # folded
    qmod = mx.mod.Module(qsym)
    qmod.bind(it.provide_data, it.provide_label, for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    it.reset()
    b = next(iter(it))
    mod.forward(b, is_train=False)
    qmod.forward(b, is_train=False)
    ref = mod.get_outputs()[0].asnumpy()
    out = qmod.get_outputs()[0].asnumpy()
    # relative fidelity of the logit field, not argmax roulette
    denom = np.abs(ref - ref.mean(axis=1, keepdims=True)).max() + 1e-6
    assert np.abs(ref - out).max() / denom < 1.0


def test_trained_net_int8_accuracy_gate():
    """The real accuracy gate: train a small conv net to high accuracy on
    separable synthetic data, quantize with entropy calibration, and assert
    int8 top-1 within 1% of fp32 (VERDICT r2 item 2's criterion, at CPU
    test scale; bench.py applies it to resnet-50 on 1024 images)."""
    rng = np.random.RandomState(7)
    n, nclass = 512, 4
    y = np.arange(n) % nclass
    # class-dependent blobs in 2 channels of an 8x8 image
    X = rng.randn(n, 8, 8, 2).astype(np.float32) * 0.3
    for i in range(n):
        c = y[i]
        X[i, c // 2 * 4:(c // 2) * 4 + 4, (c % 2) * 4:(c % 2) * 4 + 4, :] += 1.5
    it = mx.io.NDArrayIter(X, y.astype(np.float32), 64, shuffle=True)
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                            layout="NHWC", name="c1")
    r1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(r1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                        layout="NHWC", name="p1")
    c2 = mx.sym.Convolution(p1, kernel=(3, 3), num_filter=16, pad=(1, 1),
                            layout="NHWC", name="c2")
    r2 = mx.sym.Activation(c2, act_type="relu")
    fc = mx.sym.FullyConnected(r2, num_hidden=nclass, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=8,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    arg, aux = mod.get_params()

    eval_it = mx.io.NDArrayIter(X, y.astype(np.float32), 64)

    def top1(m):
        eval_it.reset()
        correct = tot = 0
        for b in eval_it:
            m.forward(b, is_train=False)
            pred = m.get_outputs()[0].asnumpy().argmax(1)
            correct += (pred == b.label[0].asnumpy()).sum()
            tot += len(pred)
        return correct / tot

    fp32_acc = top1(mod)
    assert fp32_acc > 0.9, fp32_acc  # the net actually learned

    calib_it = mx.io.NDArrayIter(X[:128], y[:128].astype(np.float32), 64)
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        net, arg, aux, calib_data=calib_it, num_calib_examples=128,
        calib_mode="entropy")
    qmod = mx.mod.Module(qsym)
    qmod.bind(eval_it.provide_data, eval_it.provide_label,
              for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux)
    int8_acc = top1(qmod)
    assert int8_acc >= fp32_acc - 0.01, (fp32_acc, int8_acc)


class TestFusedConvRequant:
    """Round 3: the qconv->bias->relu->quantize fusion pass + Pallas
    qmm_requant kernel (reference: quantize_graph_pass.cc fusion;
    quantized_conv.cu + requantize.cu collapse into one kernel)."""

    def test_qmm_requant_kernel_matches_reference(self):
        from mxnet_tpu.ops.pallas_kernels import qmm_requant
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        M, K, N = 130, 70, 40
        x = rng.randint(-127, 128, (M, K)).astype(np.int8)
        w = rng.randint(-127, 128, (K, N)).astype(np.int8)
        bias = rng.randn(N).astype(np.float32) * 10
        scale = 0.0007
        out = qmm_requant(jnp.asarray(x), jnp.asarray(w),
                          jnp.asarray(bias), scale, relu=True)
        acc = x.astype(np.int64) @ w.astype(np.int64)
        ref = np.clip(np.round(np.maximum(acc * scale + bias, 0)),
                      -127, 127).astype(np.int8)
        assert (np.asarray(out) != ref).mean() < 0.01  # rounding ties

    def test_fusion_pass_and_accuracy(self, monkeypatch):
        monkeypatch.setenv("MXTPU_FUSE_QCONV", "1")
        mx.random.seed(5)
        rng = np.random.RandomState(7)
        from mxnet_tpu.test_utils import separable_images
        X, y = separable_images(rng, 256, nclass=4, size=8, channels=2)
        it = mx.io.NDArrayIter(X, y, 64, shuffle=True)
        data = mx.sym.Variable("data")
        c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                                pad=(1, 1), layout="NHWC", name="c1")
        b1 = mx.sym.BatchNorm(c1, fix_gamma=False, axis=3, name="bn1")
        r1 = mx.sym.Activation(b1, act_type="relu")
        c2 = mx.sym.Convolution(r1, kernel=(1, 1), num_filter=16,
                                layout="NHWC", name="c2")
        r2 = mx.sym.Activation(c2, act_type="relu")
        c3 = mx.sym.Convolution(r2, kernel=(1, 1), num_filter=8,
                                layout="NHWC", name="c3")
        r3 = mx.sym.Activation(c3, act_type="relu")
        fc = mx.sym.FullyConnected(r3, num_hidden=4, name="fc")
        net = mx.sym.SoftmaxOutput(fc, name="softmax")
        mod = mx.mod.Module(net)
        # adam: the sgd+momentum version sat on a knife edge where
        # environment-level numeric noise decided convergence
        mod.fit(it, num_epoch=12, optimizer="adam",
                optimizer_params={"learning_rate": 5e-3})
        arg, aux = mod.get_params()

        ev = mx.io.NDArrayIter(X, y, 64)

        def top1(m):
            ev.reset()
            c = t = 0
            for b in ev:
                m.forward(b, is_train=False)
                p = m.get_outputs()[0].asnumpy().argmax(1)
                c += int((p == b.label[0].asnumpy()).sum())
                t += len(p)
            return c / t

        fp32 = top1(mod)
        calib = mx.io.NDArrayIter(X[:128], y[:128], 64)
        qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
            net, arg, aux, calib_data=calib, num_calib_examples=128,
            calib_mode="entropy")
        ops = [n.op for n in qsym._nodes()]
        # every conv fuses: one covers the Pallas 1x1 path, one the XLA 3x3
        assert ops.count("_contrib_quantized_conv_requant") == 3, ops
        assert "_contrib_quantized_conv" not in ops
        qmod = mx.mod.Module(qsym)
        qmod.bind(ev.provide_data, ev.provide_label, for_training=False)
        qmod.init_params(arg_params=qarg, aux_params=qaux)
        int8 = top1(qmod)
        assert fp32 > 0.9 and int8 >= fp32 - 0.02, (fp32, int8)

    def test_residual_branch_not_fused(self, monkeypatch):
        """A dequantize feeding an fp32 add (residual) must stay unfused."""
        monkeypatch.setenv("MXTPU_FUSE_QCONV", "1")
        data = mx.sym.Variable("data")
        c1 = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4,
                                layout="NHWC", no_bias=True, name="c1")
        r1 = mx.sym.Activation(c1, act_type="relu")
        c2 = mx.sym.Convolution(r1, kernel=(1, 1), num_filter=4,
                                layout="NHWC", no_bias=True, name="c2")
        res = c2 + c1  # c1 output feeds BOTH c2 and the residual add
        fc = mx.sym.FullyConnected(res, num_hidden=2, name="fc")
        net = mx.sym.SoftmaxOutput(fc, name="softmax")
        rng = np.random.RandomState(0)
        X = rng.rand(32, 6, 6, 3).astype(np.float32)
        it = mx.io.NDArrayIter(X, np.zeros(32, np.float32), 16)
        mod = mx.mod.Module(net)
        mod.bind(it.provide_data, it.provide_label, for_training=False)
        mod.init_params(initializer=mx.init.Xavier())
        arg, aux = mod.get_params()
        qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
            net, arg, aux, calib_data=it, num_calib_examples=32)
        ops = [n.op for n in qsym._nodes()]
        # c1 is consumed twice -> its chain must NOT fuse to int8-out
        assert "_contrib_quantized_conv" in ops, ops
