"""Convergence / dtype training suite (round 3, VERDICT r2 item 10).

Reference: ``tests/python/train/`` — small *real* trainings with accuracy
asserts: ``test_mlp.py`` (MLP to >95%), ``test_conv.py`` (conv net),
``test_bucketing.py`` (bucketing LM to a perplexity bound),
``test_dtype.py`` (fp16 CIFAR within tolerance of fp32 — here bf16, the
TPU reduced precision).

Synthetic separable datasets stand in for MNIST/CIFAR (zero-egress image)
— what is being asserted is the same: the full Module/Gluon training
loops actually optimize to high accuracy, in fp32 and bf16.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd

SEED = 11


def _blob_images(n, nclass, size=12, channels=3, flat=False, seed=SEED):
    """Class-separable images (shared impl: mxnet_tpu.test_utils)."""
    from mxnet_tpu.test_utils import separable_images
    X, y = separable_images(np.random.RandomState(seed), n, nclass=nclass,
                            size=size, channels=channels, noise=0.4,
                            base=1.2)
    if flat:
        X = X.reshape(n, -1)
    return X, y


def _top1(mod, it):
    it.reset()
    correct = tot = 0
    for b in it:
        mod.forward(b, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(1)
        lab = b.label[0].asnumpy()
        correct += int((pred == lab).sum())
        tot += len(pred)
    return correct / tot


def test_mlp_convergence():
    """Module.fit trains an MLP to >=95% (reference: train/test_mlp.py)."""
    X, y = _blob_images(512, 4, flat=True)
    it = mx.io.NDArrayIter(X, y, 64, shuffle=True)
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=32, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc3"), name="softmax")
    mod = mx.mod.Module(out)
    mod.fit(it, num_epoch=10,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    acc = _top1(mod, mx.io.NDArrayIter(X, y, 64))
    assert acc >= 0.95, acc


def _conv_sym(nclass, layout="NHWC", dtype=None):
    data = mx.sym.Variable("data")
    if dtype is not None:
        data = mx.sym.Cast(data, dtype=dtype, name="cast_in")
    axis = 3 if layout == "NHWC" else 1
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           layout=layout, name="c1")
    c = mx.sym.BatchNorm(c, fix_gamma=False, axis=axis, name="bn1")
    c = mx.sym.Activation(c, act_type="relu")
    c = mx.sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       layout=layout, name="p1")
    c = mx.sym.Convolution(c, kernel=(3, 3), num_filter=16, pad=(1, 1),
                           layout=layout, name="c2")
    c = mx.sym.Activation(c, act_type="relu")
    fc = mx.sym.FullyConnected(c, num_hidden=nclass, name="fc")
    if dtype is not None:
        fc = mx.sym.Cast(fc, dtype="float32", name="cast_out")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


@pytest.mark.slow
def test_conv_convergence():
    """Small conv net trains to >=95% (reference: train/test_conv.py)."""
    X, y = _blob_images(512, 4)
    it = mx.io.NDArrayIter(X, y, 64, shuffle=True)
    mod = mx.mod.Module(_conv_sym(4))
    mod.fit(it, num_epoch=8,
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    acc = _top1(mod, mx.io.NDArrayIter(X, y, 64))
    assert acc >= 0.95, acc


def test_bf16_training_matches_fp32():
    """End-to-end bf16 training (Gluon trainer, multi_precision masters)
    reaches fp32 accuracy within 2% (reference: train/test_dtype.py fp16
    CIFAR within tolerance)."""
    X, y = _blob_images(512, 4)

    def run(dtype):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, 3, padding=1, layout="NHWC"),
                gluon.nn.Activation("relu"),
                gluon.nn.MaxPool2D((2, 2), layout="NHWC"),
                gluon.nn.Conv2D(16, 3, padding=1, layout="NHWC"),
                gluon.nn.Activation("relu"),
                gluon.nn.Flatten(),
                gluon.nn.Dense(4))
        net.initialize(mx.init.Xavier())
        if dtype != "float32":
            net.cast(dtype)
        net.hybridize()
        trainer = gluon.Trainer(
            net.collect_params(), "sgd",
            {"learning_rate": 0.05, "momentum": 0.9,
             "multi_precision": dtype != "float32"})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        it = mx.io.NDArrayIter(X, y, 64, shuffle=True, shuffle_seed=SEED)
        for _epoch in range(8):
            it.reset()
            for b in it:
                x = b.data[0].astype(dtype) if dtype != "float32" \
                    else b.data[0]
                with autograd.record():
                    loss = loss_fn(net(x), b.label[0]).mean()
                loss.backward()
                trainer.step(b.data[0].shape[0])
        # eval
        correct = tot = 0
        ev = mx.io.NDArrayIter(X, y, 64)
        for b in ev:
            x = b.data[0].astype(dtype) if dtype != "float32" else b.data[0]
            pred = net(x).asnumpy().astype(np.float32).argmax(1)
            correct += int((pred == b.label[0].asnumpy()).sum())
            tot += len(pred)
        return correct / tot

    acc32 = run("float32")
    acc16 = run("bfloat16")
    assert acc32 >= 0.95, acc32
    assert acc16 >= acc32 - 0.02, (acc32, acc16)


@pytest.mark.slow
def test_bucketing_lm_convergence():
    """Bucketing char-LM trains until perplexity clearly drops
    (reference: train/test_bucketing.py's perplexity bound)."""
    rng = np.random.RandomState(SEED)
    vocab = 16
    # deterministic cyclic "language": next = (cur + 1) % vocab, so a
    # learned model approaches perplexity 1
    buckets = [8, 12]
    batches = []
    for _ in range(40):
        L = buckets[rng.randint(2)]
        start = rng.randint(vocab, size=(16,))
        seq = (start[:, None] + np.arange(L + 1)[None, :]) % vocab
        batches.append((L, seq[:, :-1].astype(np.float32),
                        seq[:, 1:].astype(np.float32)))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=16,
                               name="emb")
        cell = mx.rnn.GRUCell(24, prefix="gru_")
        outputs, _ = cell.unroll(seq_len, inputs=emb, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, 24))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="fc")
        label = mx.sym.Reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(pred, label, name="softmax"), \
            ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=max(buckets))
    dummy_key = max(buckets)
    example = [b for b in batches if b[0] == dummy_key][0]
    mod.bind([("data", example[1].shape)], [("softmax_label",
                                             example[2].shape)])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})

    def perplexity():
        tot_nll = tot_n = 0
        for L, xb, yb in batches[:10]:
            batch = mx.io.DataBatch([mx.nd.array(xb)], [mx.nd.array(yb)],
                                    bucket_key=L,
                                    provide_data=[("data", xb.shape)],
                                    provide_label=[("softmax_label",
                                                    yb.shape)])
            mod.forward(batch, is_train=False)
            probs = mod.get_outputs()[0].asnumpy()
            labels = yb.reshape(-1).astype(int)
            p = probs[np.arange(len(labels)), labels]
            tot_nll += -np.log(np.clip(p, 1e-9, None)).sum()
            tot_n += len(labels)
        return float(np.exp(tot_nll / tot_n))

    start_ppl = perplexity()
    for _epoch in range(6):
        for L, xb, yb in batches:
            batch = mx.io.DataBatch([mx.nd.array(xb)], [mx.nd.array(yb)],
                                    bucket_key=L,
                                    provide_data=[("data", xb.shape)],
                                    provide_label=[("softmax_label",
                                                    yb.shape)])
            mod.forward_backward(batch)
            mod.update()
    end_ppl = perplexity()
    assert end_ppl < 2.0, (start_ppl, end_ppl)
    assert end_ppl < start_ppl / 3
