"""Deep NN-op verification vs the torch CPU oracle (round 3).

Reference: tests/python/unittest/test_operator.py verifies Convolution/
Deconvolution/Pooling forward AND backward across stride/pad/dilate/group
configurations against hand-rolled numpy; torch's CPU kernels serve as the
same role here (analytic-vs-analytic, no finite-difference noise).  The
bf16 section checks that bf16 gradients track fp32 gradients — the dtype
axis the reference runs via test_operator_gpu.py check_consistency.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import registry

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

SEED = 0


def _t(x):
    return torch.tensor(np.asarray(x), requires_grad=False)


def _tg(x):
    t = torch.tensor(np.asarray(x))
    t.requires_grad_(True)
    return t


# (data_shape, w_shape, params) — mirrors the op_sweep_deep_cases configs
CONV_CONFIGS = [
    ((2, 4, 9, 9), (6, 4, 3, 3), dict(stride=(2, 2))),
    ((2, 4, 9, 9), (6, 4, 3, 3), dict(pad=(2, 2))),
    ((2, 4, 11, 11), (6, 4, 3, 3), dict(dilate=(2, 2))),
    ((2, 4, 8, 8), (6, 2, 3, 3), dict(num_group=2, pad=(1, 1))),
    ((2, 4, 9, 9), (5, 4, 3, 3), dict(stride=(2, 1), pad=(1, 0))),
    ((2, 4, 10, 10), (6, 4, 5, 5), dict(stride=(2, 2), pad=(2, 2))),
    ((1, 3, 7, 7), (8, 3, 1, 1), dict()),
    ((2, 4, 9, 9), (6, 4, 3, 3), dict(stride=(2, 2), dilate=(2, 2),
                                      pad=(2, 2))),
]


@pytest.mark.parametrize("dshape,wshape,cfg", CONV_CONFIGS,
                         ids=[str(i) for i in range(len(CONV_CONFIGS))])
def test_convolution_vs_torch(dshape, wshape, cfg):
    rng = np.random.RandomState(SEED)
    x = rng.randn(*dshape).astype(np.float32)
    w = rng.randn(*wshape).astype(np.float32)
    kernel = wshape[2:]
    stride = cfg.get("stride", (1, 1))
    pad = cfg.get("pad", (0, 0))
    dilate = cfg.get("dilate", (1, 1))
    groups = cfg.get("num_group", 1)
    op = registry.get("Convolution")

    def f(x_, w_):
        return op.fn(x_, w_, None, kernel=kernel, num_filter=wshape[0],
                     stride=stride, pad=pad, dilate=dilate,
                     num_group=groups, no_bias=True)

    out = f(jnp.asarray(x), jnp.asarray(w))
    xt, wt = _tg(x), _tg(w)
    ref = F.conv2d(xt, wt, stride=stride, padding=pad, dilation=dilate,
                   groups=groups)
    np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
    # backward: cotangent of ones
    dy = np.ones(ref.shape, np.float32)
    ref.backward(_t(dy))
    _, vjp = jax.vjp(f, jnp.asarray(x), jnp.asarray(w))
    dx, dw = vjp(jnp.asarray(dy))
    np.testing.assert_allclose(np.asarray(dx), xt.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(dw), wt.grad.numpy(),
                               rtol=1e-3, atol=1e-3)


DECONV_CONFIGS = [
    ((2, 4, 5, 5), (4, 6, 3, 3), dict(stride=(2, 2))),
    ((2, 4, 5, 5), (4, 6, 4, 4), dict(stride=(2, 2), pad=(1, 1))),
    ((2, 4, 5, 5), (4, 6, 3, 3), dict(stride=(2, 2), adj=(1, 1))),
    ((2, 4, 6, 6), (4, 2, 3, 3), dict(num_group=2)),
    ((2, 5, 4, 6), (5, 6, 3, 3), dict(dilate=(2, 2))),
    ((2, 3, 6, 4), (3, 4, 2, 3), dict(stride=(2, 1))),
    ((1, 2, 4, 4), (2, 3, 3, 3), dict(stride=(3, 3), pad=(1, 1),
                                      adj=(2, 2))),
    ((2, 4, 5, 5), (4, 4, 3, 3), dict(num_group=4, stride=(2, 2))),
]


@pytest.mark.parametrize("dshape,wshape,cfg", DECONV_CONFIGS,
                         ids=[str(i) for i in range(len(DECONV_CONFIGS))])
def test_deconvolution_vs_torch(dshape, wshape, cfg):
    rng = np.random.RandomState(SEED)
    x = rng.randn(*dshape).astype(np.float32)
    w = rng.randn(*wshape).astype(np.float32)
    kernel = wshape[2:]
    stride = cfg.get("stride", (1, 1))
    pad = cfg.get("pad", (0, 0))
    dilate = cfg.get("dilate", (1, 1))
    adj = cfg.get("adj", (0, 0))
    groups = cfg.get("num_group", 1)
    num_filter = wshape[1] * groups
    op = registry.get("Deconvolution")

    def f(x_, w_):
        return op.fn(x_, w_, None, kernel=kernel, num_filter=num_filter,
                     stride=stride, pad=pad, dilate=dilate, adj=adj,
                     num_group=groups, no_bias=True)

    out = f(jnp.asarray(x), jnp.asarray(w))
    xt, wt = _tg(x), _tg(w)
    ref = F.conv_transpose2d(xt, wt, stride=stride, padding=pad,
                             output_padding=adj, dilation=dilate,
                             groups=groups)
    np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
    dy = np.ones(ref.shape, np.float32)
    ref.backward(_t(dy))
    _, vjp = jax.vjp(f, jnp.asarray(x), jnp.asarray(w))
    dx, dw = vjp(jnp.asarray(dy))
    np.testing.assert_allclose(np.asarray(dx), xt.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(dw), wt.grad.numpy(),
                               rtol=1e-3, atol=1e-3)


POOL_CONFIGS = [
    (dict(kernel=(3, 3), stride=(2, 2), pool_type="max"), None),
    (dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="max"), None),
    (dict(kernel=(2, 2), stride=(2, 2), pool_type="max"), None),
    (dict(kernel=(3, 3), stride=(1, 1), pool_type="max"), None),
    (dict(kernel=(3, 3), stride=(2, 2), pool_type="avg"), None),
    (dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="avg",
          count_include_pad=True), None),
    (dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="avg",
          count_include_pad=False), None),
    (dict(kernel=(2, 2), stride=(1, 1), pool_type="avg"), None),
]


@pytest.mark.parametrize("cfg,_", POOL_CONFIGS,
                         ids=[str(i) for i in range(len(POOL_CONFIGS))])
def test_pooling_vs_torch(cfg, _):
    rng = np.random.RandomState(SEED)
    x = rng.randn(2, 3, 9, 9).astype(np.float32)
    op = registry.get("Pooling")

    def f(x_):
        return op.fn(x_, **cfg)

    out = f(jnp.asarray(x))
    xt = _tg(x)
    k, s = cfg["kernel"], cfg["stride"]
    p = cfg.get("pad", (0, 0))
    if cfg["pool_type"] == "max":
        ref = F.max_pool2d(xt, k, s, p)
    else:
        ref = F.avg_pool2d(xt, k, s, p,
                           count_include_pad=cfg.get("count_include_pad",
                                                     True))
    np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    dy = rng.rand(*ref.shape).astype(np.float32)
    ref.backward(_t(dy))
    _, vjp = jax.vjp(f, jnp.asarray(x))
    (dx,) = vjp(jnp.asarray(dy))
    np.testing.assert_allclose(np.asarray(dx), xt.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# bf16 gradients track fp32 gradients on the NN set (reference dtype axis:
# tests/python/gpu/test_operator_gpu.py check_consistency fp16-vs-fp32)
# ---------------------------------------------------------------------------
def _bf16_vs_fp32_grads(f, args, rtol=0.06, atol=0.06):
    """Relative comparison of jax.grad at bf16 vs fp32 inputs.

    The scalar is a fixed random-cotangent contraction sum(out * r): a
    sum-of-squares would be scale-invariant for the normalizers (LN/BN
    outputs have fixed norm), making dx identically ~0 and the comparison
    pure rounding noise."""
    f32 = [jnp.asarray(a, jnp.float32) for a in args]
    b16 = [jnp.asarray(a, jnp.bfloat16) for a in args]
    cot = {}

    def scalar(dtype_args):
        out = f(*dtype_args)
        out = out.astype(jnp.float32)
        if "r" not in cot:
            cot["r"] = jnp.asarray(
                np.random.RandomState(99).randn(*out.shape), jnp.float32)
        return jnp.sum(out * cot["r"])

    g32 = jax.grad(lambda *a: scalar(a), argnums=tuple(range(len(args))))(*f32)
    g16 = jax.grad(lambda *a: scalar(a), argnums=tuple(range(len(args))))(*b16)
    for a32, a16 in zip(g32, g16):
        a32 = np.asarray(a32, np.float64)
        a16 = np.asarray(a16.astype(jnp.float32), np.float64)
        scale = np.abs(a32).max() + 1e-6
        np.testing.assert_allclose(a16 / scale, a32 / scale,
                                   rtol=rtol, atol=atol)


def test_bf16_grad_convolution():
    rng = np.random.RandomState(SEED)
    x = rng.randn(2, 4, 8, 8).astype(np.float32) * 0.5
    w = rng.randn(6, 4, 3, 3).astype(np.float32) * 0.5
    op = registry.get("Convolution")
    _bf16_vs_fp32_grads(
        lambda x_, w_: op.fn(x_, w_, None, kernel=(3, 3), num_filter=6,
                             pad=(1, 1), no_bias=True), [x, w])


def test_bf16_grad_fully_connected():
    rng = np.random.RandomState(SEED)
    x = rng.randn(4, 7).astype(np.float32) * 0.5
    w = rng.randn(5, 7).astype(np.float32) * 0.5
    op = registry.get("FullyConnected")
    _bf16_vs_fp32_grads(
        lambda x_, w_: op.fn(x_, w_, None, num_hidden=5, no_bias=True),
        [x, w])


def test_bf16_grad_pooling():
    rng = np.random.RandomState(SEED)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    op = registry.get("Pooling")
    _bf16_vs_fp32_grads(
        lambda x_: op.fn(x_, kernel=(3, 3), stride=(2, 2),
                         pool_type="max"), [x])


def test_bf16_grad_batchnorm():
    rng = np.random.RandomState(SEED)
    x = rng.randn(4, 3, 6, 6).astype(np.float32)
    g = np.ones(3, np.float32)
    b = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    op = registry.get("BatchNorm")

    def f(x_, g_, b_):
        out = op.fn(x_, g_, b_, jnp.asarray(mm), jnp.asarray(mv),
                    fix_gamma=False, _train=True)
        return out[0] if isinstance(out, tuple) else out

    _bf16_vs_fp32_grads(f, [x, g, b], rtol=0.1, atol=0.1)


def test_bf16_grad_softmax():
    rng = np.random.RandomState(SEED)
    x = rng.randn(4, 10).astype(np.float32)
    op = registry.get("softmax")
    _bf16_vs_fp32_grads(lambda x_: op.fn(x_), [x])


def test_bf16_grad_layernorm():
    rng = np.random.RandomState(SEED)
    x = rng.randn(4, 8).astype(np.float32)
    g = np.ones(8, np.float32)
    b = np.zeros(8, np.float32)
    op = registry.get("LayerNorm")
    _bf16_vs_fp32_grads(lambda x_, g_, b_: op.fn(x_, g_, b_), [x, g, b],
                        rtol=0.1, atol=0.1)
