"""linalg / control-flow / quantization op tests
(reference: tests/python/unittest/test_operator.py la_op tests,
test_contrib_control_flow.py, tests/python/quantization/)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_linalg_potrf_potri():
    rng = np.random.RandomState(0)
    A = rng.randn(3, 4, 4).astype(np.float32)
    spd = A @ A.transpose(0, 2, 1) + 4 * np.eye(4, dtype=np.float32)
    L = nd.linalg_potrf(nd.array(spd)).asnumpy()
    np.testing.assert_allclose(L @ L.transpose(0, 2, 1), spd,
                               rtol=1e-3, atol=1e-4)
    inv = nd.linalg_potri(nd.array(L)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(spd), rtol=1e-2, atol=1e-3)


def test_linalg_gemm_trsm_syrk():
    rng = np.random.RandomState(1)
    A = rng.randn(2, 3, 3).astype(np.float32)
    B = rng.randn(2, 3, 3).astype(np.float32)
    C = rng.randn(2, 3, 3).astype(np.float32)
    out = nd.linalg_gemm(nd.array(A), nd.array(B), nd.array(C),
                         alpha=2.0, beta=0.5).asnumpy()
    np.testing.assert_allclose(out, 2.0 * (A @ B) + 0.5 * C, rtol=1e-5)

    L = np.tril(rng.randn(3, 3).astype(np.float32)) + 3 * np.eye(
        3, dtype=np.float32)
    X = nd.linalg_trsm(nd.array(L[None]), nd.array(B[:1])).asnumpy()
    np.testing.assert_allclose(L @ X[0], B[0], rtol=1e-4, atol=1e-4)
    # rightside: X·A = B
    Xr = nd.linalg_trsm(nd.array(L[None]), nd.array(B[:1]),
                        rightside=True).asnumpy()
    np.testing.assert_allclose(Xr[0] @ L, B[0], rtol=1e-4, atol=1e-4)

    S = nd.linalg_syrk(nd.array(A)).asnumpy()
    np.testing.assert_allclose(S, A @ A.transpose(0, 2, 1), rtol=1e-5)


def test_linalg_gelqf_syevd_det():
    rng = np.random.RandomState(2)
    A = rng.randn(2, 3, 5).astype(np.float32)
    L, Q = nd.linalg_gelqf(nd.array(A))
    np.testing.assert_allclose(L.asnumpy() @ Q.asnumpy(), A,
                               rtol=1e-4, atol=1e-4)
    # Q orthonormal rows
    qq = Q.asnumpy() @ Q.asnumpy().transpose(0, 2, 1)
    np.testing.assert_allclose(qq, np.broadcast_to(np.eye(3), (2, 3, 3)),
                               rtol=1e-4, atol=1e-4)

    S = rng.randn(4, 4).astype(np.float32)
    S = (S + S.T) / 2
    U, w = nd.linalg_syevd(nd.array(S[None]))
    wr, vr = np.linalg.eigh(S)
    np.testing.assert_allclose(np.sort(w.asnumpy()[0]), np.sort(wr),
                               rtol=1e-4, atol=1e-4)

    d = nd.linalg_det(nd.array(S[None])).asnumpy()
    np.testing.assert_allclose(d, np.linalg.det(S)[None], rtol=1e-3)


def test_foreach_scan():
    data = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = nd.contrib.foreach(body, data, nd.zeros((3,)))
    np.testing.assert_allclose(final.asnumpy(), data.asnumpy().sum(axis=0))
    np.testing.assert_allclose(outs.asnumpy()[1],
                               data.asnumpy()[:2].sum(axis=0))


def test_foreach_multi_state():
    data = nd.array(np.ones((5, 2), np.float32))

    def body(x, states):
        s0, s1 = states
        return x * s1, [s0 + x, s1 * 2]

    outs, (s0, s1) = nd.contrib.foreach(body, data,
                                        [nd.zeros((2,)), nd.ones((2,))])
    np.testing.assert_allclose(s0.asnumpy(), 5.0)
    np.testing.assert_allclose(s1.asnumpy(), 32.0)
    assert outs.shape == (5, 2)


def test_while_loop_and_cond():
    res = nd.contrib.while_loop(lambda vs: vs[0] < 10,
                                lambda vs: [vs[0] + 3],
                                [nd.array([0.0])], max_iterations=20)
    assert float(res[0].asnumpy()) == 12.0
    # max_iterations cap
    res = nd.contrib.while_loop(lambda vs: vs[0] < 1e9,
                                lambda vs: [vs[0] + 1],
                                [nd.array([0.0])], max_iterations=5)
    assert float(res[0].asnumpy()) == 5.0

    r = nd.contrib.cond(nd.array([0.0]), lambda x: x * 2, lambda x: x * 3,
                        [nd.array([5.0])])
    assert float(r.asnumpy()) == 15.0


def test_foreach_grad():
    """Gradients flow through the scanned body (lax.scan autodiff)."""
    data = nd.array(np.ones((4, 2), np.float32) * 2)
    data.attach_grad()
    with mx.autograd.record():
        outs, final = nd.contrib.foreach(
            lambda x, s: (x * s, s + x), data, nd.ones((2,)))
        loss = nd.sum(final)
    loss.backward()
    np.testing.assert_allclose(data.grad.asnumpy(), 1.0)


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    q, mn, mx_ = nd.contrib.quantize_v2(nd.array(x), out_type="int8")
    assert q.dtype == np.int8
    back = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    assert np.abs(back - x).max() / np.abs(x).max() < 0.02
    # uint8 path with explicit range
    q8, mn8, mx8 = nd.contrib.quantize(
        nd.array(x), nd.array([float(x.min())]), nd.array([float(x.max())]),
        out_type="uint8")
    assert q8.dtype == np.uint8
    back8 = nd.contrib.dequantize(q8, mn8, mx8).asnumpy()
    assert np.abs(back8 - x).max() / np.abs(x).max() < 0.02


def test_quantized_fc_vs_float():
    rng = np.random.RandomState(3)
    x = rng.randn(8, 16).astype(np.float32)
    w = rng.randn(4, 16).astype(np.float32)
    qd, dmn, dmx = nd.contrib.quantize_v2(nd.array(x), out_type="int8")
    qw, wmn, wmx = nd.contrib.quantize_v2(nd.array(w), out_type="int8")
    acc, omn, omx = nd.contrib.quantized_fully_connected(
        qd, qw, dmn, dmx, wmn, wmx, num_hidden=4, no_bias=True)
    scale = float((np.abs(x).max() / 127) * (np.abs(w).max() / 127))
    np.testing.assert_allclose(acc.asnumpy() * scale, x @ w.T,
                               rtol=0.05, atol=0.1)


def test_histogram_and_square_sum():
    x = nd.array(np.array([0.1, 0.4, 0.6, 0.9, 0.95], np.float32))
    counts, edges = nd.histogram(x, bin_cnt=2, range=(0.0, 1.0))
    np.testing.assert_array_equal(counts.asnumpy(), [2, 3])
    np.testing.assert_allclose(edges.asnumpy(), [0.0, 0.5, 1.0])
    # explicit bin edges
    counts2, edges2 = nd.histogram(
        x, nd.array(np.array([0.0, 0.5, 0.8, 1.0], np.float32)))
    np.testing.assert_array_equal(counts2.asnumpy(), [2, 1, 2])
    s = nd.square_sum(nd.array(np.array([[1.0, 2.0], [3.0, 4.0]],
                                        np.float32)), axis=1).asnumpy()
    np.testing.assert_allclose(s, [5.0, 25.0])
