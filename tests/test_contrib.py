"""contrib op tests: CTC (torch oracle), MultiBox/SSD, NMS, spatial
(reference: tests/python/unittest/test_contrib_operator.py, test_operator.py
check_ctc_loss)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _invoke(name, *args, **kwargs):
    return mx.nd.imperative_invoke(name, *args, **kwargs)


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------
def test_ctc_loss_vs_torch():
    torch = pytest.importorskip("torch")
    T, B, A, L = 12, 4, 6, 5
    rng = np.random.RandomState(0)
    logits = rng.randn(T, B, A).astype(np.float32)
    labels = rng.randint(1, A, (B, L)).astype(np.float32)
    lab_lens = np.array([5, 3, 4, 2], np.int32)
    dat_lens = np.array([12, 10, 12, 8], np.int32)
    padded = labels.copy()
    for b in range(B):
        padded[b, lab_lens[b]:] = 0
    mine = _invoke("_contrib_ctc_loss", mx.nd.array(logits),
                   mx.nd.array(padded),
                   mx.nd.array(dat_lens.astype(np.float32)),
                   mx.nd.array(lab_lens.astype(np.float32)),
                   use_data_lengths=True, use_label_lengths=True).asnumpy()
    ref = torch.nn.functional.ctc_loss(
        torch.from_numpy(logits).log_softmax(-1),
        torch.from_numpy(labels.astype(np.int64)),
        torch.from_numpy(dat_lens.astype(np.int64)),
        torch.from_numpy(lab_lens.astype(np.int64)),
        blank=0, reduction="none").numpy()
    np.testing.assert_allclose(mine, ref, rtol=1e-4, atol=1e-4)


def test_ctc_grad_vs_torch():
    torch = pytest.importorskip("torch")
    T, B, A, L = 8, 2, 5, 3
    rng = np.random.RandomState(1)
    logits = rng.randn(T, B, A).astype(np.float32)
    labels = rng.randint(1, A, (B, L)).astype(np.float32)
    x = mx.nd.array(logits)
    x.attach_grad()
    with mx.autograd.record():
        loss = mx.nd.sum(_invoke("_contrib_ctc_loss", x, mx.nd.array(labels)))
    loss.backward()
    tx = torch.from_numpy(logits).requires_grad_()
    tl = torch.nn.functional.ctc_loss(
        tx.log_softmax(-1), torch.from_numpy(labels.astype(np.int64)),
        torch.full((B,), T, dtype=torch.int64),
        torch.full((B,), L, dtype=torch.int64), blank=0, reduction="sum")
    tl.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), tx.grad.numpy(),
                               rtol=1e-3, atol=1e-4)


def test_gluon_ctc_loss_blank_last():
    """gluon.loss.CTCLoss uses blank_label='last' (reference: loss.py)."""
    torch = pytest.importorskip("torch")
    T, B, A = 10, 3, 7
    rng = np.random.RandomState(2)
    logits = rng.randn(B, T, A).astype(np.float32)  # NTC layout
    labels = rng.randint(0, A - 1, (B, 4)).astype(np.float32)
    loss = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    out = loss(mx.nd.array(logits), mx.nd.array(labels)).asnumpy()
    ref = torch.nn.functional.ctc_loss(
        torch.from_numpy(logits.transpose(1, 0, 2)).log_softmax(-1),
        torch.from_numpy(labels.astype(np.int64)),
        torch.full((B,), T, dtype=torch.int64),
        torch.full((B,), 4, dtype=torch.int64),
        blank=A - 1, reduction="none").numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# boxes
# ---------------------------------------------------------------------------
def test_multibox_prior():
    x = mx.nd.zeros((1, 3, 4, 4))
    anchors = _invoke("_contrib_MultiBoxPrior", x, sizes=(0.5, 0.25),
                      ratios=(1, 2, 0.5))
    assert anchors.shape == (1, 64, 4)
    a = anchors.asnumpy()[0]
    np.testing.assert_allclose(
        a[0], [0.125 - 0.25, 0.125 - 0.25, 0.125 + 0.25, 0.125 + 0.25],
        atol=1e-6)
    # ratio-2 anchor: wider than tall
    w2 = a[2, 2] - a[2, 0]
    h2 = a[2, 3] - a[2, 1]
    assert w2 > h2


def test_multibox_target_matching():
    x = mx.nd.zeros((1, 3, 4, 4))
    anchors = _invoke("_contrib_MultiBoxPrior", x, sizes=(0.5, 0.25),
                      ratios=(1,))
    label = np.full((1, 2, 5), -1.0, np.float32)
    label[0, 0] = [1, 0.1, 0.1, 0.4, 0.4]
    cls_pred = mx.nd.zeros((1, 3, 32))
    loc_t, loc_m, cls_t = _invoke("_contrib_MultiBoxTarget", anchors,
                                  mx.nd.array(label), cls_pred)
    ct = cls_t.asnumpy()[0]
    assert (ct == 2.0).sum() >= 1          # class 1 → target 2 (bg=0)
    assert (ct == 0).sum() + (ct == 2.0).sum() == 32
    assert loc_m.asnumpy()[0].sum() == (ct > 0).sum() * 4
    # encoded loc target finite and nonzero for positives
    lt = loc_t.asnumpy()[0].reshape(32, 4)
    pos = ct > 0
    assert np.isfinite(lt).all() and np.abs(lt[pos]).sum() > 0


def test_box_nms():
    rows = np.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5],
                      [0, 0.8, 0.12, 0.12, 0.52, 0.52],
                      [1, 0.85, 0.11, 0.11, 0.51, 0.51],
                      [0, 0.7, 0.6, 0.6, 0.9, 0.9]]], np.float32)
    out = _invoke("_contrib_box_nms", mx.nd.array(rows), overlap_thresh=0.5,
                  coord_start=2, score_index=1, id_index=0).asnumpy()[0]
    # class-aware: the class-1 box survives though it overlaps class-0 winner
    assert out[0, 1] == pytest.approx(0.9)
    assert out[2, 1] == pytest.approx(0.85)
    assert out[1, 1] == -1                  # same-class overlap suppressed
    assert out[3, 1] == pytest.approx(0.7)
    # force_suppress: class ignored
    out2 = _invoke("_contrib_box_nms", mx.nd.array(rows), overlap_thresh=0.5,
                   coord_start=2, score_index=1, id_index=0,
                   force_suppress=True).asnumpy()[0]
    assert out2[2, 1] == -1


def test_box_iou():
    a = np.array([[0, 0, 2, 2]], np.float32)
    b = np.array([[1, 1, 3, 3], [4, 4, 5, 5]], np.float32)
    iou = _invoke("_contrib_box_iou", mx.nd.array(a), mx.nd.array(b)).asnumpy()
    np.testing.assert_allclose(iou, [[1.0 / 7.0, 0.0]], rtol=1e-5)


def test_multibox_detection_decode():
    x = mx.nd.zeros((1, 3, 2, 2))
    anchors = _invoke("_contrib_MultiBoxPrior", x, sizes=(0.4,), ratios=(1,))
    N = 4
    cls_prob = np.zeros((1, 2, N), np.float32)
    cls_prob[0, 1] = [0.9, 0.2, 0.8, 0.1]
    cls_prob[0, 0] = 1 - cls_prob[0, 1]
    loc_pred = np.zeros((1, N * 4), np.float32)
    det = _invoke("_contrib_MultiBoxDetection", mx.nd.array(cls_prob),
                  mx.nd.array(loc_pred), anchors,
                  nms_threshold=0.5, threshold=0.5).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert len(kept) >= 1
    assert (kept[:, 1] >= 0.5).all()
    # zero loc_pred → decoded boxes equal the anchors
    a = anchors.asnumpy()[0]
    best = kept[np.argmax(kept[:, 1])]
    match = np.abs(a - best[2:]).sum(axis=1).min()
    assert match < 1e-5


# ---------------------------------------------------------------------------
# spatial / misc
# ---------------------------------------------------------------------------
def test_roi_align():
    data = np.zeros((1, 2, 8, 8), np.float32)
    data[0, 0] = 3.0
    data[0, 1] = 7.0
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    out = _invoke("_contrib_ROIAlign", mx.nd.array(data), mx.nd.array(rois),
                  pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(out[0, 0], 3.0, atol=1e-5)
    np.testing.assert_allclose(out[0, 1], 7.0, atol=1e-5)


def test_deformable_conv_zero_offset_equals_conv():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)
    off = np.zeros((2, 18, 7, 7), np.float32)
    dout = _invoke("_contrib_DeformableConvolution", mx.nd.array(x),
                   mx.nd.array(off), mx.nd.array(w), kernel=(3, 3),
                   num_filter=6, no_bias=True).asnumpy()
    ref = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                            num_filter=6, no_bias=True).asnumpy()
    np.testing.assert_allclose(dout, ref, rtol=1e-4, atol=1e-4)


def test_deformable_conv_shift_offset():
    """Constant offset (0, 1) equals sampling shifted input."""
    rng = np.random.RandomState(1)
    x = rng.randn(1, 1, 8, 8).astype(np.float32)
    w = np.ones((1, 1, 1, 1), np.float32)
    off = np.zeros((1, 2, 8, 8), np.float32)
    off[:, 1] = 1.0  # x-offset +1
    out = _invoke("_contrib_DeformableConvolution", mx.nd.array(x),
                  mx.nd.array(off), mx.nd.array(w), kernel=(1, 1),
                  num_filter=1, no_bias=True).asnumpy()
    np.testing.assert_allclose(out[0, 0, :, :-1], x[0, 0, :, 1:], atol=1e-5)


def test_fft_ifft_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 16).astype(np.float32)
    f = _invoke("_contrib_fft", mx.nd.array(x))
    assert f.shape == (3, 32)
    back = _invoke("_contrib_ifft", f).asnumpy()
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)


def test_adaptive_avg_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = _invoke("_contrib_AdaptiveAvgPooling2D", mx.nd.array(x),
                  output_size=(2, 2)).asnumpy()
    np.testing.assert_allclose(out[0, 0],
                               [[2.5, 4.5], [10.5, 12.5]])


def test_bilinear_resize():
    x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
    out = _invoke("_contrib_BilinearResize2D", mx.nd.array(x), height=4,
                  width=4).asnumpy()
    assert out.shape == (1, 1, 4, 4)
    assert out[0, 0, 0, 0] == pytest.approx(0.0)


def test_khatri_rao():
    a = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    b = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    out = _invoke("khatri_rao", mx.nd.array(a), mx.nd.array(b)).asnumpy()
    expect = np.array([[1, 0], [0, 2], [3, 0], [0, 4]], np.float32)
    np.testing.assert_allclose(out, expect)


def test_count_sketch():
    x = np.array([[1.0, 2.0, 3.0]], np.float32)
    h = np.array([0, 1, 0], np.float32)
    s = np.array([1, -1, 1], np.float32)
    out = _invoke("_contrib_count_sketch", mx.nd.array(x), mx.nd.array(h),
                  mx.nd.array(s), out_dim=2).asnumpy()
    np.testing.assert_allclose(out, [[4.0, -2.0]])


def test_deformable_conv_groups():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 4, 7, 7).astype(np.float32)
    w = rng.randn(4, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 18, 5, 5), np.float32)
    out = _invoke("_contrib_DeformableConvolution", mx.nd.array(x),
                  mx.nd.array(off), mx.nd.array(w), kernel=(3, 3),
                  num_filter=4, num_group=2, no_bias=True).asnumpy()
    ref = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                            num_filter=4, num_group=2,
                            no_bias=True).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_multibox_target_negative_mining():
    x = mx.nd.zeros((1, 3, 4, 4))
    anchors = _invoke("_contrib_MultiBoxPrior", x, sizes=(0.5, 0.25),
                      ratios=(1,))
    label = np.full((1, 2, 5), -1.0, np.float32)
    label[0, 0] = [1, 0.1, 0.1, 0.4, 0.4]
    cls_pred = mx.nd.array(
        np.random.RandomState(0).randn(1, 3, 32).astype(np.float32))
    _, _, cls_t = _invoke("_contrib_MultiBoxTarget", anchors,
                          mx.nd.array(label), cls_pred,
                          negative_mining_ratio=3.0, ignore_label=-1.0)
    ct = cls_t.asnumpy()[0]
    n_pos = (ct > 0).sum()
    n_neg = (ct == 0).sum()
    n_ign = (ct == -1.0).sum()
    assert n_pos >= 1
    assert n_neg <= 3 * n_pos          # mining keeps at most ratio×pos
    assert n_ign == 32 - n_pos - n_neg and n_ign > 0
