"""Registry parity vs the reference's registered op list (VERDICT r2 #6).

Extracts every NNVM_REGISTER_OP / MXNET_REGISTER_OP_PROPERTY name from the
reference tree and asserts the registry covers all of them modulo the
documented exclusion classes below (see docs/op_registry_diff.md).
Skipped when the reference tree is not present (CI without /root/reference).
"""
import glob
import os
import re

import pytest

from mxnet_tpu.ops import registry

REF = "/root/reference"

# Documented exclusions — classes of reference op names that the TPU-native
# design intentionally does not register:
EXCLUDED_PREFIXES = (
    # jax.vjp supplies every gradient; the reference registers each
    # backward as its own node (FGradient targets)
    "_backward_",
    "_contrib_backward_",
    # OpenCV host-image ops: cv2-free build (native libjpeg path instead)
    "_cv",
)
EXCLUDED_EXACT = {
    # legacy v1 ops, superseded in the reference itself
    "Convolution_v1", "Pooling_v1", "BatchNorm_v1", "CuDNNBatchNorm",
    # internal graph/executor nodes with no tensor semantics: the XLA
    # program replaces them (SURVEY §2.1 design stance)
    "_CachedOp", "_CrossDeviceCopy", "_NDArray", "_Native", "_NoGradient",
    "_CustomFunction",
    # Custom is the Python-op bridge: exposed as nd.Custom via
    # mxnet_tpu/operator.py, not a registry entry
    "Custom",
    # _foreach takes a subgraph attribute; exposed functionally as
    # nd.contrib.foreach / ops.control_flow.foreach
    "_foreach",
    "_broadcast_backward",
    # macro-definition artifact of the name scan, not an op
    "name",
}


def _reference_ops():
    names = set()
    pats = ("NNVM_REGISTER_OP", "MXNET_REGISTER_OP_PROPERTY")
    files = glob.glob(os.path.join(REF, "src/**/*.cc"), recursive=True) + \
        glob.glob(os.path.join(REF, "src/**/*.cu"), recursive=True)
    for path in files:
        try:
            txt = open(path, errors="ignore").read()
        except OSError:
            continue
        for pat in pats:
            for m in re.finditer(pat + r"\(\s*([A-Za-z0-9_\.]+)\s*[,)]",
                                 txt):
                names.add(m.group(1))
    return names


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference tree absent")
def test_registry_covers_reference_ops():
    ref = _reference_ops()
    assert len(ref) > 300  # the scan actually found the registry
    ours = set(registry.list_ops())
    missing = []
    for name in sorted(ref):
        if name in ours or name in EXCLUDED_EXACT:
            continue
        if any(name.startswith(p) for p in EXCLUDED_PREFIXES):
            continue
        # aliases: _square_sum-style underscore variants
        if name.lstrip("_") in ours:
            continue
        missing.append(name)
    assert not missing, "reference ops without a registry entry: %s" % missing
