"""Fused optimizer-update operators.

Reference strategy: tests/python/unittest/test_optimizer.py — each op is
checked against an independent numpy implementation of the reference kernel
(src/operator/optimizer_op-inl.h), and the Python Optimizer classes are
checked to produce identical trajectories through the ops.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

RTOL, ATOL = 1e-5, 1e-6


def _arrs(rng, shape=(4, 3)):
    return (rng.randn(*shape).astype(np.float32),
            rng.randn(*shape).astype(np.float32))


class TestSGDOps:
    def test_sgd_update(self):
        rng = np.random.RandomState(0)
        w, g = _arrs(rng)
        out = nd.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.01,
                            rescale_grad=0.5)
        expect = (1 - 0.1 * 0.01) * w - 0.1 * (0.5 * g)
        np.testing.assert_allclose(out.asnumpy(), expect, rtol=RTOL, atol=ATOL)

    def test_sgd_update_clip(self):
        rng = np.random.RandomState(1)
        w, g = _arrs(rng)
        out = nd.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.0,
                            rescale_grad=2.0, clip_gradient=0.5)
        expect = w - 0.1 * np.clip(2.0 * g, -0.5, 0.5)
        np.testing.assert_allclose(out.asnumpy(), expect, rtol=RTOL, atol=ATOL)

    def test_sgd_mom_update_mutates_mom(self):
        rng = np.random.RandomState(2)
        w, g = _arrs(rng)
        mom = rng.randn(4, 3).astype(np.float32)
        w_nd, mom_nd = nd.array(w), nd.array(mom)
        nd.sgd_mom_update(w_nd, nd.array(g), mom_nd, out=w_nd, lr=0.1,
                          momentum=0.9, wd=0.01)
        new_mom = 0.9 * mom - 0.1 * 0.01 * w - 0.1 * g
        np.testing.assert_allclose(mom_nd.asnumpy(), new_mom, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(w_nd.asnumpy(), w + new_mom, rtol=RTOL, atol=ATOL)

    def test_mp_sgd_mom_update(self):
        rng = np.random.RandomState(3)
        w32 = rng.randn(4, 3).astype(np.float32)
        g = rng.randn(4, 3).astype(np.float32)
        mom = np.zeros((4, 3), np.float32)
        w16 = nd.array(w32).astype("bfloat16")
        g16 = nd.array(g).astype("bfloat16")
        mom_nd, w32_nd = nd.array(mom), nd.array(w32)
        nd.mp_sgd_mom_update(w16, g16, mom_nd, w32_nd, out=w16, lr=0.1,
                             momentum=0.9, wd=0.0)
        g_f = np.asarray(g16.asnumpy(), np.float32)
        new_mom = 0.9 * mom - 0.1 * g_f
        np.testing.assert_allclose(w32_nd.asnumpy(), w32 + new_mom,
                                   rtol=1e-3, atol=1e-3)
        assert w16.dtype == np.dtype(np.float16).newbyteorder() or str(w16.dtype) == "bfloat16"


class TestAdamRMSPropFtrl:
    def test_adam_update(self):
        rng = np.random.RandomState(4)
        w, g = _arrs(rng)
        m = np.zeros_like(w); v = np.zeros_like(w)
        w_nd, m_nd, v_nd = nd.array(w), nd.array(m), nd.array(v)
        nd.adam_update(w_nd, nd.array(g), m_nd, v_nd, out=w_nd, lr=0.01,
                       beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.1)
        gg = g + 0.1 * w
        em = 0.9 * m + 0.1 * gg
        ev = 0.999 * v + 0.001 * gg * gg
        ew = w - 0.01 * em / (np.sqrt(ev) + 1e-8)
        np.testing.assert_allclose(m_nd.asnumpy(), em, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(v_nd.asnumpy(), ev, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(w_nd.asnumpy(), ew, rtol=RTOL, atol=ATOL)

    def test_rmsprop_update(self):
        rng = np.random.RandomState(5)
        w, g = _arrs(rng)
        n = np.abs(rng.randn(4, 3).astype(np.float32))
        w_nd, n_nd = nd.array(w), nd.array(n)
        nd.rmsprop_update(w_nd, nd.array(g), n_nd, out=w_nd, lr=0.01,
                          gamma1=0.95, epsilon=1e-8)
        en = 0.05 * g * g + 0.95 * n
        ew = w - 0.01 * g / np.sqrt(en + 1e-8)
        np.testing.assert_allclose(n_nd.asnumpy(), en, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(w_nd.asnumpy(), ew, rtol=RTOL, atol=ATOL)

    def test_rmspropalex_update(self):
        rng = np.random.RandomState(6)
        w, g = _arrs(rng)
        n = np.abs(rng.randn(4, 3)).astype(np.float32)
        gs = rng.randn(4, 3).astype(np.float32) * 0.1
        delta = np.zeros_like(w)
        w_nd, n_nd, g_nd, d_nd = nd.array(w), nd.array(n), nd.array(gs), nd.array(delta)
        nd.rmspropalex_update(w_nd, nd.array(g), n_nd, g_nd, d_nd, out=w_nd,
                              lr=0.01, gamma1=0.95, gamma2=0.9, epsilon=1e-4)
        en = 0.05 * g * g + 0.95 * n
        eg = 0.05 * g + 0.95 * gs
        ed = 0.9 * delta - 0.01 * g / np.sqrt(en - eg * eg + 1e-4)
        np.testing.assert_allclose(n_nd.asnumpy(), en, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(g_nd.asnumpy(), eg, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(d_nd.asnumpy(), ed, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(w_nd.asnumpy(), w + ed, rtol=1e-4, atol=1e-5)

    def test_ftrl_update(self):
        rng = np.random.RandomState(7)
        w, g = _arrs(rng)
        z = np.zeros_like(w); n = np.zeros_like(w)
        w_nd, z_nd, n_nd = nd.array(w), nd.array(z), nd.array(n)
        nd.ftrl_update(w_nd, nd.array(g), z_nd, n_nd, out=w_nd, lr=0.1,
                       lamda1=0.01, beta=1.0, wd=0.0)
        ez = z + g - (np.sqrt(n + g * g) - np.sqrt(n)) * w / 0.1
        en = n + g * g
        ew = (np.sign(ez) * 0.01 - ez) / ((1.0 + np.sqrt(en)) / 0.1) \
            * (np.abs(ez) > 0.01)
        np.testing.assert_allclose(z_nd.asnumpy(), ez, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(n_nd.asnumpy(), en, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(w_nd.asnumpy(), ew, rtol=RTOL, atol=ATOL)

    def test_ftml_update(self):
        rng = np.random.RandomState(8)
        w, g = _arrs(rng)
        d = np.zeros_like(w); v = np.zeros_like(w); z = np.zeros_like(w)
        w_nd, d_nd, v_nd, z_nd = nd.array(w), nd.array(d), nd.array(v), nd.array(z)
        nd.ftml_update(w_nd, nd.array(g), d_nd, v_nd, z_nd, out=w_nd, lr=0.01,
                       beta1=0.6, beta2=0.999, epsilon=1e-8, t=1)
        ev = 0.999 * v + 0.001 * g * g
        dt = (1 - 0.6) / 0.01 * (np.sqrt(ev / (1 - 0.999)) + 1e-8)
        ez = 0.6 * z + 0.4 * g - (dt - 0.6 * d) * w
        np.testing.assert_allclose(v_nd.asnumpy(), ev, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(d_nd.asnumpy(), dt, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(w_nd.asnumpy(), -ez / dt, rtol=1e-4, atol=1e-4)

    def test_signum_update(self):
        rng = np.random.RandomState(9)
        w, g = _arrs(rng)
        mom = np.zeros_like(w)
        w_nd, mom_nd = nd.array(w), nd.array(mom)
        nd.signum_update(w_nd, nd.array(g), mom_nd, out=w_nd, lr=0.1,
                         momentum=0.9, wd=0.01, wd_lh=0.001)
        em = 0.9 * mom - 0.1 * 0.01 * w - 0.1 * g
        ew = (1 - 0.1 * 0.001) * w + 0.1 * np.sign(em)
        np.testing.assert_allclose(mom_nd.asnumpy(), em, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(w_nd.asnumpy(), ew, rtol=RTOL, atol=ATOL)

    def test_signsgd_update(self):
        rng = np.random.RandomState(10)
        w, g = _arrs(rng)
        out = nd.signsgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.01)
        expect = (1 - 0.1 * 0.01) * w - 0.1 * np.sign(g)
        np.testing.assert_allclose(out.asnumpy(), expect, rtol=RTOL, atol=ATOL)


class TestOptimizerClassesUseOps:
    """Trajectory equivalence: Python Optimizer classes vs direct op calls."""

    @pytest.mark.parametrize("name,kwargs", [
        ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-3}),
        ("adam", {"learning_rate": 0.01}),
        ("rmsprop", {"learning_rate": 0.01, "gamma1": 0.9}),
        ("rmsprop", {"learning_rate": 0.01, "centered": True}),
        ("ftrl", {"learning_rate": 0.1}),
        ("ftml", {"learning_rate": 0.1}),
        ("signum", {"learning_rate": 0.01, "momentum": 0.9}),
        ("adagrad", {"learning_rate": 0.1}),
        ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
        ("adadelta", {}),
        ("adamax", {"learning_rate": 0.05}),
        ("nadam", {"learning_rate": 0.05}),
    ])
    def test_optimizer_converges(self, name, kwargs):
        """Each optimizer minimizes a quadratic through its op path."""
        rng = np.random.RandomState(11)
        target = rng.randn(6).astype(np.float32)
        opt = mx.optimizer.create(name, **kwargs)
        w = nd.array(np.zeros(6, np.float32))
        state = opt.create_state(0, w)
        first = None
        for i in range(200):
            g = nd.array(w.asnumpy() - target)  # grad of 0.5||w-target||^2
            if first is None:
                first = float(((w.asnumpy() - target) ** 2).sum())
            opt.update(0, w, g, state)
        last = float(((w.asnumpy() - target) ** 2).sum())
        assert last < first * 0.2, (name, first, last)

    def test_sgd_multi_precision_bf16_routes_mp_ops(self):
        opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                               multi_precision=True)
        w = nd.array(np.ones(4, np.float32)).astype("bfloat16")
        state = opt.create_state_multi_precision(0, w)
        master, mom = state
        assert master.dtype == np.float32 and mom.dtype == np.float32
        g = nd.array(np.full(4, 0.5, np.float32)).astype("bfloat16")
        opt.update_multi_precision(0, w, g, state)
        # mom = -lr*g; master = 1 + mom
        np.testing.assert_allclose(mom.asnumpy(), np.full(4, -0.05),
                                   rtol=1e-2)
        np.testing.assert_allclose(master.asnumpy(), np.full(4, 0.95),
                                   rtol=1e-2)
