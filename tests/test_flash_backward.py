"""Flash-attention Pallas backward kernels.

VERDICT r1 item 6: dq/dk/dv kernels with online-softmax recompute (O(T)
HBM), wired as the custom VJP; ring attention backward uses them.  The
memory assertion is structural: the backward jaxpr must contain no
(T×T)-shaped intermediate — the score matrix exists only blockwise inside
the kernels.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import pallas_kernels as pk


SCALE = 64 ** -0.5


def _qkv(rng, T, Tk=None, D=64, BH=2):
    Tk = Tk or T
    return (jnp.asarray(rng.randn(BH, T, D).astype(np.float32)),
            jnp.asarray(rng.randn(BH, Tk, D).astype(np.float32)),
            jnp.asarray(rng.randn(BH, Tk, D).astype(np.float32)))


@pytest.mark.parametrize("T,Tk,causal", [
    (128, 128, False), (128, 128, True),
    (192, 160, False), (200, 200, True),
])
def test_flash_grads_match_reference(T, Tk, causal):
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng, T, Tk)

    def f(q, k, v):
        return jnp.sum(jnp.sin(pk._flash_core(q, k, v, causal, SCALE)))

    def f_ref(q, k, v):
        return jnp.sum(jnp.sin(pk._attention_reference(q, k, v, causal,
                                                       SCALE)))

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-2,
                                   rtol=1e-2, err_msg=name)


def _all_avals(jaxpr, acc):
    for eqn in jaxpr.eqns:
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                acc.append(tuple(aval.shape))
        for sub in jax.core.jaxprs_in_params(eqn.params) \
                if hasattr(jax.core, "jaxprs_in_params") else []:
            _all_avals(sub, acc)
    return acc


def _shapes_in_jaxpr(closed_jaxpr):
    """All array shapes appearing anywhere in the jaxpr (incl. sub-jaxprs)."""
    seen = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    seen.append(tuple(aval.shape))
            for val in eqn.params.values():
                vals = val if isinstance(val, (list, tuple)) else [val]
                for v in vals:
                    if hasattr(v, "jaxpr"):
                        inner = v.jaxpr
                        walk(inner if hasattr(inner, "eqns") else inner.jaxpr)
    walk(closed_jaxpr.jaxpr)
    return seen


def test_flash_backward_no_quadratic_intermediate():
    """The T×T score matrix must not appear in the backward program."""
    T = 512
    rng = np.random.RandomState(1)
    q, k, v = _qkv(rng, T)

    def loss(q, k, v):
        return jnp.sum(pk._flash_core(q, k, v, False, SCALE))

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    shapes = _shapes_in_jaxpr(jaxpr)
    quadratic = [s for s in shapes if T in s and s.count(T) >= 2]
    assert not quadratic, quadratic

    # the jnp reference *does* materialize it — sanity-check the detector
    def loss_ref(q, k, v):
        return jnp.sum(pk._attention_reference(q, k, v, False, SCALE))

    jaxpr_ref = jax.make_jaxpr(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    shapes_ref = _shapes_in_jaxpr(jaxpr_ref)
    assert any(T in s and s.count(T) >= 2 for s in shapes_ref)


def test_flash_lse_matches_reference():
    rng = np.random.RandomState(2)
    q, k, v = _qkv(rng, 128)
    _, lse = pk.flash_forward_with_lse(q, k, v, False, SCALE)
    s = jnp.einsum("btd,bsd->bts", q, k) * SCALE
    lse_ref = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_bf16_backward():
    rng = np.random.RandomState(3)
    q, k, v = _qkv(rng, 128)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def f(q, k, v):
        return jnp.sum(pk._flash_core(q, k, v, True, SCALE)
                       .astype(jnp.float32))

    g = jax.grad(f, argnums=(0, 1, 2))(qb, kb, vb)
    g_ref = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b), atol=0.15, rtol=0.1)
