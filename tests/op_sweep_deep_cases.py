"""Deep configuration sweeps merged into test_op_sweep.CASES (round 3).

Reference: tests/python/unittest/test_operator.py runs conv across
stride/pad/dilate/group combinations, reductions across axis sets, and
indexing across mode/edge-index cases — one configuration per op is not a
sweep.  Each entry here appends cases to the base sweep; the harness runs
forward (+oracle when given), finite-difference gradients, and jit-vs-eager
consistency for every case.

Oracles: numpy where direct; conv/deconv/pooling configs rely on the
FD-gradient + jit/eager checks here and get torch forward oracles in
tests/test_op_deep_nn.py.
"""
import numpy as np

from test_op_sweep import C, r, rpos


def _r(*shape):
    return r(*shape)


def _idx(shape, high, dtype=np.float32):
    def gen(rng):
        return [rng.randint(0, high, shape).astype(dtype)]
    return gen


# conv/deconv cases whose FD check is disabled produce |scalar| large
# enough that fp32 central-difference cancellation noise exceeds the
# harness tolerance; their backward is covered analytically vs torch in
# test_op_deep_nn.py.
DEEP_CASES = {
    # ---- Convolution: stride x pad x dilate x groups x layout x rank ----
    # (reference: test_operator.py test_convolution_options)
    "Convolution": [
        C(lambda rng: [rng.randn(2, 4, 9, 9).astype(np.float32),
                       rng.randn(6, 4, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "stride": (2, 2),
                  "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 4, 9, 9).astype(np.float32),
                       rng.randn(6, 4, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "pad": (2, 2),
                  "no_bias": True}, tol=1e-4, grad=False),
        C(lambda rng: [rng.randn(2, 4, 11, 11).astype(np.float32),
                       rng.randn(6, 4, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "dilate": (2, 2),
                  "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 4, 8, 8).astype(np.float32),
                       rng.randn(6, 2, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "num_group": 2,
                  "pad": (1, 1), "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 8, 8, 4).astype(np.float32),
                       rng.randn(6, 3, 3, 4).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "layout": "NHWC",
                  "pad": (1, 1), "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 3, 10).astype(np.float32),
                       rng.randn(4, 3, 5).astype(np.float32)],
          params={"kernel": (5,), "num_filter": 4, "stride": (2,),
                  "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(1, 2, 5, 6, 7).astype(np.float32),
                       rng.randn(4, 2, 3, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3, 3), "num_filter": 4, "pad": (1, 1, 1),
                  "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 4, 9, 9).astype(np.float32),
                       rng.randn(5, 4, 3, 2).astype(np.float32)],
          params={"kernel": (3, 2), "num_filter": 5, "stride": (2, 1),
                  "pad": (1, 0), "no_bias": True}, tol=1e-4),
    ],
    # ---- Deconvolution -------------------------------------------------
    "Deconvolution": [
        C(lambda rng: [rng.randn(2, 4, 5, 5).astype(np.float32),
                       rng.randn(4, 6, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "stride": (2, 2),
                  "no_bias": True}, tol=1e-4, grad=False),
        C(lambda rng: [rng.randn(2, 4, 5, 5).astype(np.float32),
                       rng.randn(4, 6, 4, 4).astype(np.float32)],
          params={"kernel": (4, 4), "num_filter": 6, "stride": (2, 2),
                  "pad": (1, 1), "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 4, 5, 5).astype(np.float32),
                       rng.randn(4, 6, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "stride": (2, 2),
                  "adj": (1, 1), "no_bias": True}, tol=1e-4, grad=False),
        C(lambda rng: [rng.randn(2, 4, 6, 6).astype(np.float32),
                       rng.randn(4, 2, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 4, "num_group": 2,
                  "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 3, 7).astype(np.float32),
                       rng.randn(3, 5, 4).astype(np.float32)],
          params={"kernel": (4,), "num_filter": 5, "stride": (2,),
                  "pad": (1,), "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(1, 2, 4, 4, 4).astype(np.float32),
                       rng.randn(2, 3, 3, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3, 3), "num_filter": 3, "stride": (2, 2, 2),
                  "no_bias": True}, tol=1e-4, grad=False),
        C(lambda rng: [rng.randn(2, 5, 4, 6).astype(np.float32),
                       rng.randn(5, 6, 3, 3).astype(np.float32)],
          params={"kernel": (3, 3), "num_filter": 6, "dilate": (2, 2),
                  "no_bias": True}, tol=1e-4),
        C(lambda rng: [rng.randn(2, 3, 6, 4).astype(np.float32),
                       rng.randn(3, 4, 2, 3).astype(np.float32)],
          params={"kernel": (2, 3), "num_filter": 4, "stride": (2, 1),
                  "no_bias": True}, tol=1e-4),
    ],
    # ---- Pooling: type x stride x pad x convention x layout x rank ------
    "Pooling": [
        C(r(2, 3, 9, 9), params={"kernel": (3, 3), "stride": (2, 2),
                                 "pool_type": "max"}),
        C(r(2, 3, 9, 9), params={"kernel": (3, 3), "stride": (2, 2),
                                 "pad": (1, 1), "pool_type": "avg"}),
        C(r(2, 3, 9, 9), params={"kernel": (3, 3), "stride": (2, 2),
                                 "pad": (1, 1), "pool_type": "avg",
                                 "count_include_pad": False}),
        C(r(2, 3, 8, 8), params={"kernel": (2, 2), "stride": (2, 2),
                                 "pool_type": "sum"}),
        C(r(2, 3, 9, 9), params={"kernel": (3, 3), "stride": (2, 2),
                                 "pooling_convention": "full",
                                 "pool_type": "max"}),
        C(r(2, 9, 9, 3), params={"kernel": (3, 3), "stride": (2, 2),
                                 "layout": "NHWC", "pool_type": "max"}),
        C(r(2, 3, 12), params={"kernel": (4,), "stride": (3,),
                               "pool_type": "avg"}),
        C(r(1, 2, 5, 6, 7), params={"kernel": (2, 2, 2), "stride": (2, 2, 2),
                                    "pool_type": "max"}),
        C(r(2, 3, 7, 7), params={"global_pool": True, "pool_type": "avg"}),
        C(r(2, 3, 7, 7), params={"kernel": (3, 3), "stride": (1, 1),
                                 "pool_type": "lp"}),
    ],
    # ---- FullyConnected -------------------------------------------------
    "FullyConnected": [
        C(lambda rng: [rng.randn(4, 7).astype(np.float32),
                       rng.randn(5, 7).astype(np.float32)],
          params={"num_hidden": 5, "no_bias": True},
          oracle=lambda x, w, num_hidden, no_bias: x @ w.T),
        C(lambda rng: [rng.randn(2, 3, 4).astype(np.float32),
                       rng.randn(6, 4).astype(np.float32)],
          params={"num_hidden": 6, "flatten": False, "no_bias": True},
          oracle=lambda x, w, num_hidden, flatten, no_bias: x @ w.T),
        C(lambda rng: [rng.randn(2, 3, 4).astype(np.float32),
                       rng.randn(6, 12).astype(np.float32)],
          params={"num_hidden": 6, "no_bias": True},
          oracle=lambda x, w, num_hidden, no_bias:
          x.reshape(2, 12) @ w.T),
    ],
    # ---- BatchNorm / LayerNorm ------------------------------------------
    "BatchNorm": [
        C(lambda rng: [rng.randn(2, 6, 6, 3).astype(np.float32),
                       np.ones(3, np.float32), np.zeros(3, np.float32),
                       np.zeros(3, np.float32), np.ones(3, np.float32)],
          params={"axis": 3, "fix_gamma": False}, grad=False),
        C(lambda rng: [rng.randn(2, 3, 5).astype(np.float32),
                       np.ones(3, np.float32), np.zeros(3, np.float32),
                       np.zeros(3, np.float32), np.ones(3, np.float32)],
          params={"fix_gamma": False}, grad=False),
        C(lambda rng: [rng.randn(2, 3, 6, 6).astype(np.float32),
                       rng.rand(3).astype(np.float32) + 0.5,
                       rng.randn(3).astype(np.float32),
                       rng.randn(3).astype(np.float32),
                       rng.rand(3).astype(np.float32) + 0.5],
          params={"use_global_stats": True, "fix_gamma": False},
          grad=False),
    ],
    "LayerNorm": [
        C(lambda rng: [rng.randn(2, 3, 4).astype(np.float32),
                       np.ones(3, np.float32), np.zeros(3, np.float32)],
          params={"axis": 1}, grad=False),
        C(lambda rng: [rng.randn(5, 8).astype(np.float32),
                       np.ones(8, np.float32), np.zeros(8, np.float32)],
          params={"axis": -1, "eps": 1e-3}, grad=False),
    ],
    # ---- activations ----------------------------------------------------
    "Activation": [
        C(r(3, 4), params={"act_type": "sigmoid"},
          oracle=lambda x, act_type: 1 / (1 + np.exp(-x))),
        C(r(3, 4), params={"act_type": "tanh"},
          oracle=lambda x, act_type: np.tanh(x)),
        C(r(3, 4), params={"act_type": "softrelu"},
          oracle=lambda x, act_type: np.log1p(np.exp(x))),
        C(r(3, 4), params={"act_type": "softsign"},
          oracle=lambda x, act_type: x / (1 + np.abs(x))),
    ],
    "LeakyReLU": [
        C(r(3, 4), params={"act_type": "leaky", "slope": 0.1},
          oracle=lambda x, act_type, slope: np.where(x > 0, x, slope * x)),
        C(r(3, 4), params={"act_type": "elu", "slope": 1.0},
          oracle=lambda x, act_type, slope:
          np.where(x > 0, x, slope * (np.exp(x) - 1))),
    ],
    "softmax": [
        C(r(3, 4, 5), params={"axis": 0}),
        C(r(3, 4), params={"temperature": 2.0}),
        C(r(2, 3, 4, 5), params={"axis": 2}),
    ],
    "log_softmax": [
        C(r(3, 4, 5), params={"axis": 0}),
        C(r(3, 4), params={"axis": -1}),
    ],
    # ---- reductions: axis combos, negative axis, degenerate shapes ------
    # (reference: test_operator.py test_reduce)
    "sum": [
        C(r(3, 4, 5), params={"axis": (0, 2)},
          oracle=lambda x, axis: x.sum(axis=axis)),
        C(r(3, 4, 5), params={"axis": -1},
          oracle=lambda x, axis: x.sum(axis=-1)),
        C(r(3, 4), params={},
          oracle=lambda x: np.asarray(x.sum())),
        C(r(3, 1, 5), params={"axis": 1, "keepdims": True},
          oracle=lambda x, axis, keepdims: x.sum(axis=1, keepdims=True)),
        C(r(1,), params={"axis": 0},
          oracle=lambda x, axis: np.asarray(x.sum())),
    ],
    "mean": [
        C(r(3, 4, 5), params={"axis": (0, 1)},
          oracle=lambda x, axis: x.mean(axis=axis)),
        C(r(3, 4, 5), params={"axis": -2, "keepdims": True},
          oracle=lambda x, axis, keepdims: x.mean(axis=-2, keepdims=True)),
        C(r(2, 3), params={"exclude": True, "axis": 0},
          oracle=lambda x, axis, exclude: x.mean(axis=1)),
    ],
    "prod": [
        C(r(2, 3, 4), params={"axis": (1, 2)},
          oracle=lambda x, axis: x.prod(axis=axis)),
        C(r(5,), params={"axis": 0},
          oracle=lambda x, axis: np.asarray(x.prod())),
    ],
    "max": [
        C(r(3, 4, 5), params={"axis": (0, 2)},
          oracle=lambda x, axis: x.max(axis=axis)),
        C(r(3, 4), params={"axis": -1, "keepdims": True},
          oracle=lambda x, axis, keepdims: x.max(axis=-1, keepdims=True)),
    ],
    "min": [
        C(r(3, 4, 5), params={"axis": (1, 2)},
          oracle=lambda x, axis: x.min(axis=axis)),
        C(r(7,), params={"axis": 0},
          oracle=lambda x, axis: np.asarray(x.min())),
    ],
    "norm": [
        C(r(3, 4, 5), params={"axis": (1, 2)},
          oracle=lambda x, axis: np.sqrt((x * x).sum(axis=axis))),
        C(r(3, 4), params={"ord": 2},
          oracle=lambda x, ord: np.asarray(np.sqrt((x * x).sum()))),
    ],
    "argmax": [
        C(r(3, 4, 5), params={"axis": 2, "keepdims": True},
          oracle=lambda x, axis, keepdims:
          x.argmax(axis=2)[:, :, None].astype(np.float32), grad=False),
        C(r(6,), params={"axis": 0},
          oracle=lambda x, axis: np.asarray(float(x.argmax())), grad=False),
    ],
    "argmin": [
        C(r(3, 4, 5), params={"axis": 0},
          oracle=lambda x, axis: x.argmin(axis=0).astype(np.float32),
          grad=False),
    ],
    # ---- broadcast: both-sides, degenerate, 3-D -------------------------
    "broadcast_add": [
        C(lambda rng: [rng.randn(3, 1).astype(np.float32),
                       rng.randn(1, 4).astype(np.float32)],
          oracle=np.add),
        C(lambda rng: [rng.randn(2, 1, 4).astype(np.float32),
                       rng.randn(2, 3, 1).astype(np.float32)],
          oracle=np.add),
        C(lambda rng: [rng.randn(1, 1).astype(np.float32),
                       rng.randn(3, 4).astype(np.float32)],
          oracle=np.add),
    ],
    "broadcast_mul": [
        C(lambda rng: [rng.randn(3, 1).astype(np.float32),
                       rng.randn(1, 4).astype(np.float32)],
          oracle=np.multiply),
        C(lambda rng: [rng.randn(2, 3, 4).astype(np.float32),
                       rng.randn(1, 3, 1).astype(np.float32)],
          oracle=np.multiply),
    ],
    "broadcast_sub": [
        C(lambda rng: [rng.randn(2, 1, 1).astype(np.float32),
                       rng.randn(1, 3, 4).astype(np.float32)],
          oracle=np.subtract),
    ],
    "broadcast_div": [
        C(lambda rng: [rng.randn(3, 1).astype(np.float32),
                       rng.rand(1, 4).astype(np.float32) + 0.5],
          oracle=np.divide),
    ],
    "broadcast_to": [
        C(r(1, 4), params={"shape": (3, 4)},
          oracle=lambda x, shape: np.broadcast_to(x, shape)),
        C(r(3, 1, 1), params={"shape": (3, 2, 5)},
          oracle=lambda x, shape: np.broadcast_to(x, shape)),
    ],
    "broadcast_axis": [
        C(r(1, 4), params={"axis": 0, "size": 3},
          oracle=lambda x, axis, size: np.broadcast_to(x, (3, 4))),
    ],
    # ---- indexing: modes, negative, duplicate, out-of-range -------------
    # (reference: test_operator.py test_take / indexing_op.h)
    "take": [
        C(lambda rng: [rng.randn(5, 4).astype(np.float32),
                       np.array([0, 4, 2], np.int32)],
          params={"axis": 0},
          oracle=lambda a, i, axis: a[i.astype(int)]),
        C(lambda rng: [rng.randn(5, 4).astype(np.float32),
                       np.array([1, 1, 1], np.int32)],  # duplicates
          params={"axis": 0},
          oracle=lambda a, i, axis: a[i.astype(int)]),
        C(lambda rng: [rng.randn(5, 4).astype(np.float32),
                       np.array([7., -9.], np.float32)],  # out of range
          params={"axis": 0, "mode": "clip"},
          oracle=lambda a, i, axis, mode:
          a[np.clip(i.astype(int), 0, 4)], grad=False),
        C(lambda rng: [rng.randn(5, 4).astype(np.float32),
                       np.array([6., -1.], np.float32)],
          params={"axis": 0, "mode": "wrap"},
          oracle=lambda a, i, axis, mode: a[i.astype(int) % 5], grad=False),
        C(lambda rng: [rng.randn(3, 5).astype(np.float32),
                       np.array([[0, 4], [2, 2]], np.int32)],
          params={"axis": 1},
          oracle=lambda a, i, axis: np.take(a, i.astype(int), axis=1)),
    ],
    "Embedding": [
        C(lambda rng: [np.array([1, 3, 1, 0], np.int32),
                       rng.randn(5, 6).astype(np.float32)],
          params={"input_dim": 5, "output_dim": 6},
          oracle=lambda i, w, input_dim, output_dim: w[i.astype(int)]),
    ],
    "batch_take": [
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       np.array([0., 3., 2.], np.float32)],
          oracle=lambda a, i: a[np.arange(3), i.astype(int)], grad=False),
    ],
    "pick": [
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       np.array([0, 3, 1], np.int32)],
          params={"axis": 1},
          oracle=lambda a, i, axis: a[np.arange(3), i.astype(int)]),
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       np.array([9., -1., 1.], np.float32)],
          params={"axis": 1, "mode": "clip"},
          oracle=lambda a, i, axis, mode:
          a[np.arange(3), np.clip(i.astype(int), 0, 3)], grad=False),
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       np.array([0, 3, 1], np.int32)],
          params={"axis": 1, "keepdims": True},
          oracle=lambda a, i, axis, keepdims:
          a[np.arange(3), i.astype(int)][:, None]),
    ],
    "gather_nd": [
        C(lambda rng: [rng.randn(4, 5).astype(np.float32),
                       np.array([[0, 3, 3], [1, 1, 4]], np.int32)],
          oracle=lambda d, i: d[i[0].astype(int), i[1].astype(int)]),
        C(lambda rng: [rng.randn(4, 5, 2).astype(np.float32),
                       np.array([[2, 2]], np.int32)],
          oracle=lambda d, i: d[i[0].astype(int)]),
    ],
    "scatter_nd": [
        C(lambda rng: [rng.randn(3).astype(np.float32),
                       np.array([[0., 2., 0.]], np.float32)],  # dup index 0
          params={"shape": (4,)}, grad=False),
    ],
    "one_hot": [
        C(lambda rng: [np.array([0., 2., 1.], np.float32)],
          params={"depth": 4},
          oracle=lambda i, depth: np.eye(4, dtype=np.float32)[i.astype(int)],
          grad=False),
        C(lambda rng: [np.array([1., 3.], np.float32)],
          params={"depth": 4, "on_value": 2.0, "off_value": -1.0},
          oracle=lambda i, depth, on_value, off_value:
          np.where(np.eye(4)[i.astype(int)] > 0, 2.0, -1.0)
          .astype(np.float32), grad=False),
    ],
    "slice": [
        C(r(5, 6), params={"begin": (1, 2), "end": (4, 5)},
          oracle=lambda x, begin, end: x[1:4, 2:5]),
        C(r(5, 6), params={"begin": (0, None), "end": (None, None),
                           "step": (2, 1)},
          oracle=lambda x, begin, end, step: x[::2, :]),
        C(r(5, 6), params={"begin": (-3, 0), "end": (None, 6)},
          oracle=lambda x, begin, end: x[-3:, :]),
        C(r(5, 6), params={"begin": (4, None), "end": (0, None),
                           "step": (-2, 1)},
          oracle=lambda x, begin, end, step: x[4:0:-2, :]),
    ],
    "slice_axis": [
        C(r(4, 5, 6), params={"axis": 1, "begin": 1, "end": 4},
          oracle=lambda x, axis, begin, end: x[:, 1:4]),
        C(r(4, 5, 6), params={"axis": -1, "begin": 0, "end": 3},
          oracle=lambda x, axis, begin, end: x[..., :3]),
        C(r(4, 5), params={"axis": 0, "begin": -2, "end": None},
          oracle=lambda x, axis, begin, end: x[-2:]),
    ],
    "reverse": [
        C(r(3, 4), params={"axis": 0}, oracle=lambda x, axis: x[::-1]),
        C(r(3, 4, 5), params={"axis": (0, 2)},
          oracle=lambda x, axis: x[::-1, :, ::-1]),
    ],
    "tile": [
        C(r(2, 3), params={"reps": (2, 2)},
          oracle=lambda x, reps: np.tile(x, reps)),
        C(r(3,), params={"reps": (2, 3)},
          oracle=lambda x, reps: np.tile(x, (2, 3))),
    ],
    "repeat": [
        C(r(2, 3), params={"repeats": 2, "axis": 1},
          oracle=lambda x, repeats, axis: np.repeat(x, 2, axis=1)),
        C(r(2, 3), params={"repeats": 3},
          oracle=lambda x, repeats: np.repeat(x, 3)),
    ],
    # ---- shape manipulation edge cases ----------------------------------
    "Reshape": [
        C(r(2, 3, 4), params={"shape": (0, -1)},
          oracle=lambda x, shape: x.reshape(2, 12)),
        C(r(2, 3, 4), params={"shape": (-1, 0)},
          oracle=lambda x, shape: x.reshape(8, 3)),
        C(r(2, 3, 4), params={"shape": (0, 0, 2, 2)},
          oracle=lambda x, shape: x.reshape(2, 3, 2, 2)),
        C(r(2, 12), params={"shape": (0, -4, 3, 4)},
          oracle=lambda x, shape: x.reshape(2, 3, 4)),
        C(r(2, 3, 4), params={"shape": (-3, 0)},
          oracle=lambda x, shape: x.reshape(6, 4)),
    ],
    "transpose": [
        C(r(2, 3, 4), params={"axes": (2, 0, 1)},
          oracle=lambda x, axes: x.transpose(axes)),
        C(r(2, 3), params={},
          oracle=lambda x: x.T),
        C(r(2, 3, 4, 5), params={"axes": (0, 3, 1, 2)},
          oracle=lambda x, axes: x.transpose(axes)),
    ],
    "expand_dims": [
        C(r(2, 3), params={"axis": 0},
          oracle=lambda x, axis: x[None]),
        C(r(2, 3), params={"axis": -1},
          oracle=lambda x, axis: x[..., None]),
        C(r(2, 3), params={"axis": 2},
          oracle=lambda x, axis: x[:, :, None]),
    ],
    "squeeze": [
        C(r(1, 3, 1, 4), params={},
          oracle=lambda x: x.reshape(3, 4)),
        C(r(1, 3, 1, 4), params={"axis": 2},
          oracle=lambda x, axis: x.reshape(1, 3, 4)),
    ],
    "Flatten": [
        C(r(2, 3, 4, 5), params={},
          oracle=lambda x: x.reshape(2, 60)),
        C(r(4, 1), params={}, oracle=lambda x: x),
    ],
    "stack": [
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       rng.randn(3, 4).astype(np.float32)],
          params={"axis": 1},
          oracle=lambda a, b, axis: np.stack([a, b], axis=1)),
    ],
    "Concat": [
        C(lambda rng: [rng.randn(2, 3).astype(np.float32),
                       rng.randn(2, 5).astype(np.float32)],
          params={"dim": 1},
          oracle=lambda a, b, dim: np.concatenate([a, b], axis=1)),
        C(lambda rng: [rng.randn(1, 3).astype(np.float32),
                       rng.randn(4, 3).astype(np.float32),
                       rng.randn(2, 3).astype(np.float32)],
          params={"dim": 0},
          oracle=lambda *xs, dim: np.concatenate(xs, axis=0)),
    ],
    "split": [
        C(r(4, 6), params={"num_outputs": 3, "axis": 1}, grad=False),
        C(r(6, 4), params={"num_outputs": 2, "axis": 0,
                           "squeeze_axis": False}, grad=False),
    ],
    "flip": [
        C(r(3, 4), params={"axis": 1}, oracle=lambda x, axis: x[:, ::-1]),
    ],
    "Pad": [
        C(r(2, 3, 4, 5), params={"mode": "constant",
                                 "pad_width": (0, 0, 0, 0, 1, 1, 2, 2),
                                 "constant_value": 0.5},
          oracle=lambda x, mode, pad_width, constant_value:
          np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)),
                 constant_values=0.5)),
        C(r(2, 3, 4, 5), params={"mode": "edge",
                                 "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)},
          oracle=lambda x, mode, pad_width:
          np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)), mode="edge")),
        C(r(2, 3, 4, 5), params={"mode": "reflect",
                                 "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)},
          oracle=lambda x, mode, pad_width:
          np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)), mode="reflect")),
    ],
    # ---- dot family: transpose flags ------------------------------------
    "dot": [
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       rng.randn(3, 5).astype(np.float32)],
          params={"transpose_a": True},
          oracle=lambda a, b, transpose_a: a.T @ b),
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       rng.randn(5, 4).astype(np.float32)],
          params={"transpose_b": True},
          oracle=lambda a, b, transpose_b: a @ b.T),
        C(lambda rng: [rng.randn(4, 3).astype(np.float32),
                       rng.randn(5, 4).astype(np.float32)],
          params={"transpose_a": True, "transpose_b": True},
          oracle=lambda a, b, transpose_a, transpose_b: a.T @ b.T),
    ],
    "batch_dot": [
        C(lambda rng: [rng.randn(2, 3, 4).astype(np.float32),
                       rng.randn(2, 3, 5).astype(np.float32)],
          params={"transpose_a": True},
          oracle=lambda a, b, transpose_a:
          np.einsum("bij,bik->bjk", a, b)),
        C(lambda rng: [rng.randn(2, 3, 4).astype(np.float32),
                       rng.randn(2, 5, 4).astype(np.float32)],
          params={"transpose_b": True},
          oracle=lambda a, b, transpose_b:
          np.einsum("bij,bkj->bik", a, b)),
    ],
    # ---- misc degenerate shapes -----------------------------------------
    "where": [
        C(lambda rng: [(rng.rand(3, 4) > 0.5).astype(np.float32),
                       rng.randn(3, 4).astype(np.float32),
                       rng.randn(3, 4).astype(np.float32)],
          oracle=lambda c, a, b: np.where(c > 0, a, b)),
    ],
    "clip": [
        C(r(3, 4), params={"a_min": 0.0, "a_max": 0.0},
          oracle=lambda x, a_min, a_max: np.zeros_like(x), grad=False),
    ],
    "abs": [
        C(r(1, 1), oracle=np.abs),
        C(r(7,), oracle=np.abs),
    ],
    "_add": [
        C(lambda rng: [rng.randn(1).astype(np.float32),
                       rng.randn(1).astype(np.float32)], oracle=np.add),
    ],
    "SequenceMask": [
        C(lambda rng: [rng.randn(4, 2, 3).astype(np.float32),
                       np.array([2., 4.], np.float32)],
          params={"use_sequence_length": True, "value": -1.0}, grad=False),
    ],
    "SequenceLast": [
        C(lambda rng: [rng.randn(4, 2, 3).astype(np.float32),
                       np.array([2., 4.], np.float32)],
          params={"use_sequence_length": True}, grad=False),
    ],
    "SequenceReverse": [
        C(lambda rng: [rng.randn(4, 2, 3).astype(np.float32),
                       np.array([2., 4.], np.float32)],
          params={"use_sequence_length": True}, grad=False),
    ],
}


# ---- round-3 operator tail (VERDICT r2 Missing #2) ----------------------
DEEP_CASES.update({
    "hard_sigmoid": [
        C(r(3, 4), oracle=lambda x: np.clip(0.2 * x + 0.5, 0, 1)),
        C(r(5,), params={"alpha": 0.5, "beta": 0.0},
          oracle=lambda x, alpha, beta: np.clip(0.5 * x, 0, 1)),
    ],
    "_ravel_multi_index": [
        C(lambda rng: [np.array([[1., 2.], [0., 1.]], np.float32)],
          params={"shape": (3, 4)},
          oracle=lambda d, shape: np.asarray(
              np.ravel_multi_index(d.astype(int), shape), np.float32),
          grad=False),
    ],
    "_unravel_index": [
        C(lambda rng: [np.array([4., 9.], np.float32)],
          params={"shape": (3, 4)},
          oracle=lambda d, shape: np.asarray(
              np.unravel_index(d.astype(int), shape), np.float32),
          grad=False),
    ],
    "_slice_assign": [
        C(lambda rng: [rng.randn(4, 5).astype(np.float32),
                       rng.randn(2, 2).astype(np.float32)],
          params={"begin": (1, 2), "end": (3, 4)},
          oracle=lambda a, b, begin, end:
          np.concatenate([a[:1], np.concatenate(
              [a[1:3, :2], b, a[1:3, 4:]], axis=1), a[3:]], axis=0)),
    ],
    "_slice_assign_scalar": [
        C(r(4, 5), params={"scalar": 7.0, "begin": (1,), "end": (3,)},
          oracle=lambda x, scalar, begin, end: np.concatenate(
              [x[:1], np.full((2, 5), 7.0, np.float32), x[3:]], axis=0)),
    ],
    "_sample_poisson": [
        C(lambda rng: [np.array([1.0, 20.0], np.float32)],
          params={"shape": (500,)}, grad=False),
    ],
    "_sample_exponential": [
        C(lambda rng: [np.array([1.0, 10.0], np.float32)],
          params={"shape": (500,)}, grad=False),
    ],
    "_sample_negative_binomial": [
        C(lambda rng: [np.array([5.0], np.float32),
                       np.array([0.5], np.float32)],
          params={"shape": (500,)}, grad=False),
    ],
    "_sample_generalized_negative_binomial": [
        C(lambda rng: [np.array([4.0], np.float32),
                       np.array([0.25], np.float32)],
          params={"shape": (500,)}, grad=False),
    ],
    "_image_to_tensor": [
        C(lambda rng: [rng.randint(0, 255, (4, 5, 3)).astype(np.uint8)],
          oracle=lambda x: (x.astype(np.float32) / 255.0)
          .transpose(2, 0, 1), grad=False),
        C(lambda rng: [rng.randint(0, 255, (2, 4, 5, 3)).astype(np.uint8)],
          oracle=lambda x: (x.astype(np.float32) / 255.0)
          .transpose(0, 3, 1, 2), grad=False),
    ],
    "_image_normalize": [
        C(r(3, 4, 5), params={"mean": (0.1, 0.2, 0.3), "std": (1., 2., 4.)},
          oracle=lambda x, mean, std:
          (x - np.asarray(mean, np.float32).reshape(3, 1, 1)) /
          np.asarray(std, np.float32).reshape(3, 1, 1)),
    ],
    "_contrib_div_sqrt_dim": [
        C(r(2, 16), oracle=lambda x: x / 4.0),
    ],
    "_contrib_quantized_flatten": [
        C(lambda rng: [rng.randint(-127, 127, (2, 3, 4)).astype(np.int8),
                       np.array([-1.0], np.float32),
                       np.array([1.0], np.float32)], grad=False),
    ],
    "_contrib_PSROIPooling": [
        C(lambda rng: [rng.randn(1, 8, 8, 8).astype(np.float32),
                       np.array([[0, 0, 0, 7, 7]], np.float32)],
          params={"spatial_scale": 1.0, "output_dim": 2, "pooled_size": 2},
          grad=False),
    ],
    "cast_storage": [
        C(lambda rng: [np.array([[0, 0], [1, 2], [0, 0], [3, 0]],
                                np.float32)],
          params={"stype": "row_sparse"}, grad=False),
        C(lambda rng: [np.array([[0, 1], [2, 0]], np.float32)],
          params={"stype": "csr"}, grad=False),
    ],
    "_sparse_retain": [
        C(lambda rng: [rng.randn(3, 2).astype(np.float32),
                       np.array([0, 2, 5], np.int64),
                       np.array([2, 3, 5], np.int64)], grad=False),
    ],
})


DEEP_CASES.update({
    "_copyto": [C(r(3, 4), oracle=lambda x: x)],
    "_grad_add": [C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                                 rng.randn(3, 4).astype(np.float32)],
                    oracle=np.add)],
    "_identity_with_attr_like_rhs": [
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       rng.randn(3, 4).astype(np.float32)],
          oracle=lambda a, b: a)],
    "_scatter_plus_scalar": [C(r(3, 4), params={"scalar": 2.0},
                               oracle=lambda x, scalar: x + 2.0)],
    "_scatter_minus_scalar": [C(r(3, 4), params={"scalar": 2.0},
                                oracle=lambda x, scalar: x - 2.0)],
    "_scatter_elemwise_div": [
        C(lambda rng: [rng.randn(3, 4).astype(np.float32),
                       rng.rand(3, 4).astype(np.float32) + 0.5],
          oracle=np.divide)],
    "_contrib_quadratic": [
        C(r(3, 4), params={"a": 1.0, "b": 2.0, "c": 3.0},
          oracle=lambda x, a, b, c: x * x + 2 * x + 3)],
    "IdentityAttachKLSparseReg": [
        C(lambda rng: [rng.rand(4, 3).astype(np.float32)],
          oracle=lambda x: x, grad=False)],
})
