"""Codegen tier (mxnet_tpu/analysis/codegen.py "mxgen" +
ops/generated_kernels.py; docs/fusion.md "Generated kernels"): the
shipped top-3 chains of the transformer train-step and ZeRO-1 tapes
lower deterministically into registered Pallas kernels with
auto-declared costs, every generated kernel equals its tape reference
through the REAL pallas path (interpret, whole-array AND row-tiled),
GEN001 names unlowerable chains, GEN002 names unproven registrations,
COST006 names a lost auto-declared cost entry, the MXGEN_LOWER_EXACT
mislowering seam is killed through the unmodified STATIC_BUDGETS.json
gate (subprocess rc=2, FUS001 named), the seeded autotune cache
replays bitwise across subprocess runs (and rebuilds from corruption),
and the `--codegen` CLI/schema-6 JSON section round-trips through
tools/parse_log.py.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.analysis import codegen as cg
from mxnet_tpu.analysis.cost import KERNEL_COSTS, build_tape
from mxnet_tpu.analysis.fusion import analyze_tape_fusion
from mxnet_tpu.ops import generated_kernels as gen

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FLOAT_TOL = 1e-5

SHIPPED_NAMES = [
    "_gen_tp_transformer_top1", "_gen_tp_transformer_top2",
    "_gen_tp_transformer_top3", "_gen_zero1_top1", "_gen_zero1_top2",
    "_gen_zero1_top3",
]


def _cpu_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_CHAOS", None)
    env.pop("MXTPU_MXGEN_CACHE", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({k: str(v) for k, v in extra.items()})
    return env


# ---------------------------------------------------------------------------
# the shipped lowering: top-3 per tape, proven, zero hand-written code
# ---------------------------------------------------------------------------
def test_shipped_chains_lower_and_prove():
    kernels = {gk.name: gk for gk in gen.build_shipped_generated()}
    assert sorted(kernels) == sorted(SHIPPED_NAMES)
    for gk in kernels.values():
        assert gk.src is not None
        assert gk.equivalence_ok, (gk.name, gk.equivalence_err)
        assert gk.bytes_saved > 0
        assert gk.bytes_saved == gk.unfused_bytes - gk.fused_bytes
    # registration == registry == cost table
    assert set(SHIPPED_NAMES) <= set(gen.GENERATED_KERNELS)
    assert set(SHIPPED_NAMES) <= set(KERNEL_COSTS)


def test_generated_cost_entry_is_chain_parity_by_construction():
    """The auto-declared KERNEL_COSTS entry copies the chain's per-call
    fused-byte split verbatim — FUS001 parity is an identity."""
    gen.build_shipped_generated()
    lowered = {lk.name: lk for lk in cg.shipped_lowered()}
    for name in SHIPPED_NAMES:
        gk = gen.GENERATED_KERNELS[name]
        c = KERNEL_COSTS[name](None)
        assert c["bytes_read"] == gk.bytes_read
        assert c["bytes_written"] == gk.bytes_written
        lk = lowered[name]
        per_call = int(lk.fused_bytes) // max(int(lk.scale), 1)
        assert c["bytes_read"] + c["bytes_written"] == per_call
        assert c["flops"] == gk.flops
        assert c["transcendentals"] == gk.transcendentals


def test_lowering_is_deterministic():
    """Same tape + chain -> byte-identical emitted source and external
    ordering (the plan the CLI prints is reproducible)."""
    tape = cg.shipped_tape("zero1")
    report = analyze_tape_fusion(tape)
    chain = report.chains[0]
    a = cg.lower_chain(tape, chain, "_det_probe", tag="zero1", rank=1)
    b = cg.lower_chain(tape, chain, "_det_probe", tag="zero1", rank=1)
    assert a.src == b.src
    assert a.ext_in == b.ext_in and a.ext_out == b.ext_out
    assert a.fused_bytes == b.fused_bytes
    assert a.bytes_saved == b.bytes_saved


def test_pallas_path_matches_tape_reference_per_kernel():
    """The REAL pl.pallas_call path (interpret on the host) equals the
    independent tape interpreter within the PR-15 tolerance, for every
    shipped generated kernel."""
    kernels = gen.build_shipped_generated()
    lowered = {lk.name: lk for lk in cg.shipped_lowered()}
    for gk in kernels:
        lk = lowered[gk.name]
        inputs = cg.seeded_inputs(lk.in_avals, cg.EQUIV_SEED)
        want = cg.reference_outputs(lk, inputs)
        got = gen.generated_call(gk, *inputs, interpret=True)
        for w, g in zip(want, got):
            w, g = np.asarray(w), np.asarray(g)
            assert w.shape == g.shape and w.dtype == g.dtype
            if np.issubdtype(w.dtype, np.floating):
                assert np.allclose(w, g, rtol=FLOAT_TOL,
                                   atol=FLOAT_TOL), gk.name
            else:
                assert np.array_equal(w, g), gk.name


def test_tiled_path_matches_whole_array_at_every_rung():
    """The flat-tileable kernel's row-tiled grid agrees with the
    whole-array call at every autotune-ladder rung (padding rows are
    computed then discarded, never observed)."""
    kernels = gen.build_shipped_generated()
    lowered = {lk.name: lk for lk in cg.shipped_lowered()}
    tileable = [gk for gk in kernels
                if cg.flat_tileable(lowered[gk.name])]
    assert tileable, "no flat-tileable shipped kernel"
    for gk in tileable:
        lk = lowered[gk.name]
        inputs = cg.seeded_inputs(lk.in_avals, cg.EQUIV_SEED)
        whole = gen.generated_call(gk, *inputs, interpret=True)
        for br in cg.AUTOTUNE_LADDER:
            tiled = gen.generated_call(gk, *inputs, interpret=True,
                                       block_rows=br)
            for w, t in zip(whole, tiled):
                assert np.allclose(np.asarray(w), np.asarray(t),
                                   rtol=FLOAT_TOL, atol=FLOAT_TOL), \
                    (gk.name, br)


# ---------------------------------------------------------------------------
# GEN001 / GEN002 / COST006: the static gates around the registry
# ---------------------------------------------------------------------------
def test_gen001_chain_outside_provable_set():
    """A chain carrying an op outside LOWERABLE (argmax epilogue — the
    fusion pass fuses it, mxgen refuses to prove it) does not lower:
    src None + a GEN001 finding naming the prim."""
    def f(x):
        return jnp.argmax(x * 2.0 + 1.0)

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((256,), jnp.float32))
    tape = build_tape(closed)
    report = analyze_tape_fusion(tape)
    chains = [c for c in report.chains
              if any(p.startswith("argmax") or p.startswith("reduce_and")
                     or p.startswith("argmin") for p in c.prims)]
    assert chains, "fusion pass no longer chains the argmax epilogue"
    lk = cg.lower_chain(tape, chains[0], "_gen001_probe")
    assert lk.src is None
    assert any(f_.rule_id == "GEN001" for f_ in lk.findings)


def test_gen002_unproven_registration_flagged():
    """A registered kernel whose equivalence flag dropped is a GEN002
    error in the lint sweep — and the clean registry stays clean."""
    gen.build_shipped_generated()
    assert cg.lint_generated_kernels() == []
    gk = gen.GENERATED_KERNELS[SHIPPED_NAMES[0]]
    try:
        gk.equivalence_ok = False
        findings = cg.lint_generated_kernels()
        assert any(f.rule_id == "GEN002" and f.subject == gk.name
                   for f in findings)
        # and the rule is mutable via --disable like every other rule
        assert cg.lint_generated_kernels(disable=("GEN002",)) == []
    finally:
        gk.equivalence_ok = True
    assert cg.lint_generated_kernels() == []


def test_cost006_lost_auto_declared_cost_entry():
    """Deleting a generated kernel's KERNEL_COSTS entry is a COST006
    gate error (the fusion.py registry diff), not a silent skip."""
    from mxnet_tpu.analysis import lint_kernel_costs

    gen.build_shipped_generated()
    assert lint_kernel_costs() == []
    name = SHIPPED_NAMES[-1]
    saved = KERNEL_COSTS.pop(name)
    try:
        findings = lint_kernel_costs()
        assert any(f.rule_id == "COST006" and f.subject == name
                   for f in findings), findings
    finally:
        KERNEL_COSTS[name] = saved
    assert lint_kernel_costs() == []


# ---------------------------------------------------------------------------
# the mislowering mutation seam through the UNMODIFIED budget gate
# ---------------------------------------------------------------------------
def test_mislowering_seam_kills_budget_gate(tmp_path):
    """Acceptance: MXGEN_LOWER_EXACT=False (the emitter lowers `sub`
    as `add` in the emitted text only) fails the unmodified
    STATIC_BUDGETS.json gate rc=2 naming FUS001 — from a subprocess."""
    script = tmp_path / "mutate.py"
    script.write_text(
        "import os, sys\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu.analysis import codegen\n"
        "codegen.MXGEN_LOWER_EXACT = False\n"
        "from mxnet_tpu.analysis.__main__ import main\n"
        "sys.exit(main(['--cost', '--budget', %r]))\n"
        % os.path.join(REPO, "STATIC_BUDGETS.json"))
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=REPO,
                          env=_cpu_env(), timeout=600)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "FUS001" in proc.stdout
    assert "codegen_generated_kernels" in proc.stdout


def test_codegen_chains_rows_pinned_in_budget_file():
    """Every shipped chain's bytes-saved is pinned in the checked-in
    STATIC_BUDGETS.json codegen_chains section, and matches the live
    lowering exactly."""
    with open(os.path.join(REPO, "STATIC_BUDGETS.json")) as f:
        budget = json.load(f)
    assert budget["schema_version"] >= 4
    rows = budget["codegen_chains"]
    assert rows == cg.shipped_chain_rows()
    assert sorted(rows) == sorted(SHIPPED_NAMES)
    assert all(v > 0 for v in rows.values())


# ---------------------------------------------------------------------------
# the autotune cache: seeded, replayed bitwise, rebuilt from corruption
# ---------------------------------------------------------------------------
_AUTOTUNE_SRC = """\
import json, sys
from mxnet_tpu.ops import generated_kernels as gen
kernels = gen.build_shipped_generated(autotune=True)
print(json.dumps({k.name: k.block_rows for k in kernels},
                 sort_keys=True))
"""


def _run_autotune(cache_path):
    proc = subprocess.run(
        [sys.executable, "-c", _AUTOTUNE_SRC],
        capture_output=True, text=True, cwd=REPO, timeout=600,
        env=_cpu_env(MXTPU_MXGEN_CACHE=cache_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_autotune_cache_replayed_bitwise_across_runs(tmp_path):
    """Same seed + same ladder: run 1 measures and writes the cache;
    run 2 REPLAYS it — byte-identical cache file (no rewrite) and the
    same block choice.  A corrupt cache file is rebuilt, not trusted."""
    cache = str(tmp_path / "mxgen_cache.json")
    picks1 = _run_autotune(cache)
    tiled1 = {k: v for k, v in picks1.items() if v is not None}
    assert tiled1, "no kernel was autotuned"
    assert all(v in cg.AUTOTUNE_LADDER for v in tiled1.values())
    with open(cache, "rb") as f:
        bytes1 = f.read()
    obj = json.loads(bytes1)
    assert obj["schema"] == cg.AUTOTUNE_CACHE_SCHEMA
    assert obj["seed"] == cg.AUTOTUNE_SEED
    assert obj["ladder"] == list(cg.AUTOTUNE_LADDER)
    assert set(tiled1) <= set(obj["kernels"])

    picks2 = _run_autotune(cache)
    assert picks2 == picks1
    with open(cache, "rb") as f:
        assert f.read() == bytes1     # replayed, never rewritten

    # corruption is rebuilt from fresh measurements, never trusted
    with open(cache, "w") as f:
        f.write("{not json")
    picks3 = _run_autotune(cache)
    assert set(k for k, v in picks3.items() if v is not None) \
        == set(tiled1)
    with open(cache) as f:
        rebuilt = json.load(f)
    assert rebuilt["schema"] == cg.AUTOTUNE_CACHE_SCHEMA
    assert all(rebuilt["kernels"][k]["block_rows"]
               in list(cg.AUTOTUNE_LADDER) for k in tiled1)


def test_autotune_mismatched_seed_cache_not_trusted(tmp_path):
    """A cache written under a different seed/ladder is invalid — the
    loader refuses it rather than replaying stale choices."""
    cache = str(tmp_path / "stale.json")
    with open(cache, "w") as f:
        json.dump({"schema": cg.AUTOTUNE_CACHE_SCHEMA, "seed": 1,
                   "ladder": [2, 4], "kernels": {"x": {
                       "block_rows": 2, "t_ns": [1]}}}, f)
    assert cg._load_cache(cache, cg.AUTOTUNE_SEED,
                          cg.AUTOTUNE_LADDER) is None
    assert cg._load_cache(cache, 1, (2, 4)) is not None


# ---------------------------------------------------------------------------
# CLI / schema-6 JSON / parse_log wiring
# ---------------------------------------------------------------------------
def test_codegen_cli_plan_and_json_section():
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--cost",
         "--codegen", "--model", "mlp_infer"],
        capture_output=True, text=True, cwd=REPO, env=_cpu_env(),
        timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "mxgen: 6 shipped chain(s) lowered" in proc.stdout
    for name in SHIPPED_NAMES:
        assert name in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--cost",
         "--codegen", "--json", "--model", "mlp_infer"],
        capture_output=True, text=True, cwd=REPO, env=_cpu_env(),
        timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema_version"] == 6
    plans = payload["codegen"]
    assert [p["name"] for p in plans] == SHIPPED_NAMES
    for p in plans:
        assert p["lowerable"] and p["findings"] == []
        assert p["bytes_saved"] > 0 and p["src"]
    # without --codegen the section is absent (pre-6 consumers
    # unaffected)
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--cost", "--json",
         "--model", "mlp_infer"],
        capture_output=True, text=True, cwd=REPO, env=_cpu_env(),
        timeout=600)
    assert "codegen" not in json.loads(proc.stdout)


def test_parse_log_reads_codegen_rows():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    doc = {"version": 1, "schema_version": 6, "findings": [],
           "codegen": [{"name": "_gen_zero1_top2", "bytes_saved": 9,
                        "lowerable": True}]}
    rows = dict(parse_log.parse_analysis_json(doc))
    assert rows["codegen.n_kernels"] == 1
    assert rows["codegen._gen_zero1_top2.bytes_saved"] == 9
    assert rows["codegen._gen_zero1_top2.lowerable"] == 1


def test_bench_compare_gates_codegen_keys(tmp_path):
    """The three codegen bench keys gate from their first two live
    rounds: a collapsing speedup, shrinking modeled win, or numerics
    drop all regress."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_compare
    finally:
        sys.path.pop(0)

    def rec(n, **parsed):
        path = tmp_path / ("BENCH_r%02d.json" % n)
        path.write_text(json.dumps(
            {"n": n, "cmd": "x", "rc": 0, "parsed": parsed}))
        return str(path)

    files = [
        rec(1, codegen_generated_speedup_host=40.0,
            codegen_modeled_bytes_saved_pct=84.0,
            codegen_numerics_ok=1.0),
        rec(2, codegen_generated_speedup_host=41.0,
            codegen_modeled_bytes_saved_pct=84.2,
            codegen_numerics_ok=1.0),
    ]
    ok = rec(3, codegen_generated_speedup_host=39.0,
             codegen_modeled_bytes_saved_pct=84.1,
             codegen_numerics_ok=1.0)
    report = bench_compare.compare(files + [ok])
    assert report["regressions"] == []
    assert report["gates"]["codegen_generated_speedup_host"][
        "verdict"] == "ok"
    bad = rec(4, codegen_generated_speedup_host=20.0,
              codegen_modeled_bytes_saved_pct=84.1,
              codegen_numerics_ok=0.0)
    report = bench_compare.compare(files + [ok, bad])
    assert "codegen_generated_speedup_host" in report["regressions"]
    assert "codegen_numerics_ok" in report["regressions"]
    assert "codegen_modeled_bytes_saved_pct" not in report["regressions"]
