"""Autograd tests (reference: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal


def test_basic_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2 * x
    y.backward()
    assert_almost_equal(x.grad, 2 * np.array([1, 2, 3]) + 2)


def test_chain_and_branches():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y * x + y  # z = 2x^2 + 2x
        loss = z.sum()
    loss.backward()
    assert_almost_equal(x.grad, 4 * x.asnumpy() + 2)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 20.0]))
    assert_almost_equal(x.grad, np.array([30.0, 60.0]))


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert_almost_equal(x.grad, np.array([6.0, 6.0]))


def test_detach_and_stop_gradient():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, np.array([4.0]))  # only d(y_const * x)/dx = y = 4

    x2 = nd.array([3.0])
    x2.attach_grad()
    with autograd.record():
        z2 = nd.BlockGrad(x2 * x2) * x2
    z2.backward()
    assert_almost_equal(x2.grad, np.array([9.0]))


def test_training_and_recording_state():
    assert not autograd.is_recording()
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_pause_no_graph():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        with autograd.pause():
            y = x * 2
    assert y._entry is None


def test_mark_variables():
    x = nd.array([1.0, 2.0])
    g = nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, np.array([2.0, 4.0]))


def test_grad_function():
    x = nd.array([2.0, 3.0])
    out = autograd.grad(_f(x), [x])
    # grad computed on fresh graph

def _f(x):
    x.attach_grad()
    with autograd.record():
        return (x * x).sum()


def test_grad_api():
    x = nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
    (gx,) = autograd.grad(y, [x]),
    assert_almost_equal(gx[0], 3 * np.array([4.0, 9.0]))


def test_higher_order_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
        gx = autograd.grad(y, [x], create_graph=True)[0]
        z = gx.sum()
    z.backward()
    # d/dx (3x^2) = 6x = 12
    assert_almost_equal(x.grad, np.array([12.0]))


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.5, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-5)


def test_multi_output_op_grad():
    x = nd.array(np.random.rand(2, 6).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        parts = nd.split(x, num_outputs=3, axis=1)
        loss = (parts[0] * 1 + parts[1] * 2 + parts[2] * 3).sum()
    loss.backward()
    ref = np.concatenate([np.full((2, 2), i, np.float32) for i in (1, 2, 3)], axis=1)
    assert_almost_equal(x.grad, ref)


def test_softmax_output_backward():
    x = nd.array(np.random.rand(4, 5).astype(np.float32))
    label = nd.array([0.0, 1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, label)
    out.backward()
    p = np.exp(x.asnumpy() - x.asnumpy().max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    assert_almost_equal(x.grad, p - onehot, rtol=1e-5)
