"""mxnet_tpu.serving.decode: the paged KV pool, the continuous-batching
DecodeBatcher, and the fleet's decode surface (ISSUE 17).

The host-side contracts:

- PagePool determinism: ascending allocation, scratch page 0 reserved,
  LIFO recycling, double-free refused — the page-table arithmetic the
  batching schedule's byte-identical reruns lean on;
- continuous batching is DETERMINISTIC: a paused batcher fed a seeded
  burst (pinned ``token_time_hint_ms`` so the tokens-remaining shed
  arithmetic has no wall-clock in it) replays to byte-identical
  ``schedule_events()`` and token-exact results, with deadline sheds
  confined to the admission path and the bronze tier;
- chaos at ``serving.batch`` fails the active sequences WITHOUT leaking
  a single KV page, and the worker keeps serving;
- fleet admission (the satellite bugfix): fixed-shape runners price the
  max-over-buckets worst case, decode runners their pages-based
  ``admission_hbm_bytes()`` override — both flow through SRV004;
- the SRV006 trace-constant lint and the ``tools/capacity.py --tokens``
  sizing mode ride the same decode_step budget row the gate pins;
- headline: a TRAINED TransformerLM served through the fleet under a
  seeded concurrent mixed-length burst — token-exact vs the sequential
  no-batching reference, gold p99-per-token inside its declared SLO,
  sheds confined to bronze, zero steady-state recompiles, zero leaked
  pages after drain.
"""
import json
import os
import sys
import threading

import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.chaos import ChaosError
from mxnet_tpu.serving.batcher import RequestShed
from mxnet_tpu.serving.decode import (DecodeBatcher, DecodeRunner,
                                      NoPagesFree, PagePool)
from mxnet_tpu.serving.fleet import ModelFleet
from mxnet_tpu.transformer import TransformerLMConfig
from mxnet_tpu.transformer.decode import DecodeProgram

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

CFG = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
           seq_len=32)


def _runner(slots=2, warmup=True):
    prog = DecodeProgram(TransformerLMConfig(**CFG), page_size=8)
    return DecodeRunner(prog, prog.program.init_params(0), slots=slots,
                        prefill_buckets=(8, 16, 32), warmup=warmup)


@pytest.fixture(scope="module")
def runner():
    return _runner()


def _fresh_pool(runner):
    """Swap in a pristine pool: the determinism reruns must start from
    identical free-list state, and stale cache content is provably
    harmless (attention never reads past ``length``)."""
    runner.pool = PagePool(1 + runner.slots * runner.pages_per_seq,
                           runner.page_size, runner.pool.bytes_per_page)


# -- PagePool ---------------------------------------------------------------
def test_page_pool_ascending_alloc_and_scratch_reserved():
    pool = PagePool(9, 8, 1024)
    assert pool.available == 8          # page 0 never handed out
    a = pool.alloc(3)
    b = pool.alloc(2)
    assert a == [1, 2, 3] and b == [4, 5]
    assert 0 not in a + b
    assert pool.pages_in_use == 5
    d = pool.describe()
    assert d["n_pages"] == 9 and d["available"] == 3
    assert d["pages_in_use"] == 5 and d["bytes_per_page"] == 1024


def test_page_pool_lifo_recycle_is_deterministic():
    pool = PagePool(9, 8, 1024)
    a = pool.alloc(3)
    pool.free(a)
    assert pool.alloc(3) == a           # freed pages come back first,
    assert pool.pages_for(1) == 1       # in the same order
    assert pool.pages_for(8) == 1 and pool.pages_for(9) == 2


def test_page_pool_double_free_raises():
    pool = PagePool(5, 8, 64)
    pages = pool.alloc(2)
    pool.free(pages)
    with pytest.raises(MXNetError):
        pool.free(pages)                # already on the free list
    with pytest.raises(MXNetError):
        pool.free([0])                  # the scratch page, never leased
    assert pool.pages_in_use == 0


def test_page_pool_exhaustion_raises_no_pages_free():
    pool = PagePool(4, 8, 64)
    pool.alloc(3)
    with pytest.raises(NoPagesFree):
        pool.alloc(1)
    assert pool.available == 0 and pool.pages_in_use == 3


# -- continuous-batching determinism ----------------------------------------
# (prompt_len, max_new, tier, deadline_ms): two bronze requests carry a
# 1ms deadline — with the pinned 5ms/token hint their modeled completion
# (>= max_new * 5ms) always exceeds it, so they shed AT ADMISSION on
# every run; deadline never touches the wall-clock sweep path.
_BURST = [(5, 6, "gold", None), (11, 6, "silver", None),
          (3, 6, "bronze", 1), (8, 6, "gold", 60000),
          (16, 6, "bronze", 1), (24, 6, "silver", None),
          (7, 6, "bronze", None)]


def _burst_prompts():
    rng = np.random.RandomState(7)
    return [rng.randint(1, CFG["vocab_size"], size=n).astype(np.int32)
            for n, _, _, _ in _BURST]


def _run_burst(runner, prompts):
    _fresh_pool(runner)
    batcher = DecodeBatcher(runner, max_queue=32,
                            token_time_hint_ms=5.0, paused=True)
    futs, shed = {}, []
    for i, ((_, max_new, tier, deadline), prompt) in enumerate(
            zip(_BURST, prompts)):
        try:
            futs[i] = batcher.submit(prompt, max_new_tokens=max_new,
                                     tier=tier, deadline_ms=deadline)
        except RequestShed as e:
            assert e.shed_at == "admit"
            shed.append(i)
    batcher.release()
    outs = {i: np.asarray(f.result(120.0), np.int32)
            for i, f in futs.items()}
    batcher.drain(timeout=60.0)
    return outs, tuple(shed), batcher.schedule_events(), batcher.stats


def test_continuous_batching_schedule_is_byte_identical(runner):
    prompts = _burst_prompts()
    refs = {i: runner.reference_decode(p, _BURST[i][1])
            for i, p in enumerate(prompts)}           # idle runner

    out1, shed1, ev1, st1 = _run_burst(runner, prompts)
    out2, shed2, ev2, st2 = _run_burst(runner, prompts)

    assert ev1 == ev2, "schedule diverged across identical reruns"
    assert shed1 == shed2 == (2, 4)                   # the bronze pair
    assert set(out1) == set(out2) == {0, 1, 3, 5, 6}
    for i in out1:
        assert np.array_equal(out1[i], out2[i])
        assert np.array_equal(out1[i], refs[i]), \
            "request %d diverged from the sequential reference" % i
    # every join/leave/shed is on the tape, sheds confined to admission
    events = {e for e, _, _ in ev1}
    assert events == {"join", "leave", "shed-admit"}
    assert sum(1 for e, _, _ in ev1 if e == "join") == 5
    for st in (st1, st2):
        assert st._shed_by_tier == {"bronze": 2}
        assert st.swept_total == 0
        assert st.sequences_done_total == 5
    assert runner.pool.pages_in_use == 0
    assert runner.recompiles_since_warmup() == 0


def test_chaos_step_fault_reclaims_every_page(runner):
    """A raise mid-decode-step fails every ACTIVE sequence, frees their
    pages, and the worker keeps serving the queue — then a post-chaos
    decode works on the same batcher."""
    prompts = _burst_prompts()[:4]
    refs = [runner.reference_decode(p, 6) for p in prompts]
    _fresh_pool(runner)
    batcher = DecodeBatcher(runner, max_queue=32,
                            token_time_hint_ms=5.0, paused=True)
    chaos.install([chaos.Fault("serving.batch", 2, "raise")])
    try:
        futs = [batcher.submit(p, max_new_tokens=6) for p in prompts]
        batcher.release()
        failed, served = [], []
        for i, f in enumerate(futs):
            try:
                out = np.asarray(f.result(120.0), np.int32)
            except ChaosError:
                failed.append(i)
            else:
                served.append(i)
                assert np.array_equal(out, refs[i])
        # slots=2: requests 0+1 were active at step 2 when the fault
        # fired; 2+3 joined after and decoded clean
        assert failed == [0, 1] and served == [2, 3]
        assert len(chaos.triggered()) == 1
        # the worker is still alive: decode again through the chaos'd
        # batcher, token-exact
        out = np.asarray(batcher.decode(prompts[0], max_new_tokens=6,
                                        timeout=120.0), np.int32)
        assert np.array_equal(out, refs[0])
    finally:
        chaos.uninstall()
    batcher.drain(timeout=60.0)
    assert runner.pool.pages_in_use == 0, \
        "%d KV pages leaked across the fault" % runner.pool.pages_in_use


# -- fleet admission (the satellite bugfix) ----------------------------------
def _module_runner():
    import mxnet_tpu as mx
    from mxnet_tpu.serving import ModelRunner
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=3, name="fc2"),
        name="softmax")
    mod = mx.mod.Module(sym)
    mod.bind(data_shapes=[("data", (8, 8))],
             label_shapes=[("softmax_label", (8,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    return ModelRunner(mod, buckets=(1, 4, 8))


def test_fixed_runner_admission_is_max_over_buckets():
    r = _module_runner()
    cost = r.modeled_cost()
    assert set(cost) == {1, 4, 8}
    worst = max(row["peak_hbm_bytes"] for row in cost.values())
    assert r.modeled_peak_hbm() == worst
    # the regression: admission charges the worst bucket, not bucket[0]
    assert r.admission_hbm_bytes() == worst
    assert worst >= cost[1]["peak_hbm_bytes"]


def test_fleet_prefers_decode_pages_bound_and_enforces_cap():
    r = _runner(warmup=False)
    adm = r.admission_hbm_bytes()
    # pages-based: weights + the KV pool + one step's working set
    assert adm > r.pool.n_pages * r.pool.bytes_per_page
    # over-cap registration is refused statically — before any batcher
    # (or page-table owner) exists
    tight = ModelFleet(hbm_cap_bytes=adm - 1)
    with pytest.raises(MXNetError, match="over cap"):
        tight.register_decode("lm", r)
    fleet = ModelFleet(hbm_cap_bytes=adm + 1)
    entry = fleet.register_decode("lm", r)
    assert entry.hbm_bytes == adm
    assert fleet.modeled_hbm_total() == adm
    with pytest.raises(MXNetError, match="already registered"):
        fleet.register_decode("lm", r)
    entry.batcher.force_drain()


# -- capacity --tokens (the PR-12 simulator rides the budget row) ------------
def test_capacity_cli_tokens_mode(capsys):
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import capacity
    base = ["--dau", "20000", "--slo-ms", "2000", "--tokens",
            "--max-new-tokens", "8", "--slots", "4", "--json"]
    assert capacity.main(base) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["replicas"] >= 1
    # derived deterministically from the gated decode_step budget row
    from mxnet_tpu.mlops.simulator import token_ms_from_decode_step
    with open(os.path.join(REPO, "STATIC_BUDGETS.json")) as f:
        row = json.load(f)["models"]["decode_step"]
    want = token_ms_from_decode_step(
        {"flops": row["flops"], "bytes_read": row["peak_hbm_bytes"],
         "bytes_written": 0})
    assert out["token_ms"] == pytest.approx(want)
    # a pinned --token-ms overrides the derivation verbatim
    assert capacity.main(base + ["--token-ms", "2.0"]) == 0
    out2 = json.loads(capsys.readouterr().out)
    assert out2["token_ms"] == pytest.approx(2.0)


# -- the gated bench contract ------------------------------------------------
@pytest.mark.slow
def test_decode_bench_contract_keys():
    from mxnet_tpu.serving.decode_bench import decode_bench
    r = decode_bench(n_requests=8, concurrency=2, slots=2)
    assert r["decode_numerics_ok"] == 1
    assert r["decode_recompiles"] == 0
    assert r["decode_pages_leaked"] == 0
    assert r["decode_tokens_total"] > 0
    assert r["decode_tokens_per_sec_host"] > 0
    assert r["decode_p99_per_token_ms"] >= r["decode_p50_per_token_ms"]


# -- SRV006 ------------------------------------------------------------------
_BAD_DECODE = """
import jax.numpy as jnp

def decode_step(cache, length):
    if length > 4:%s
        return jnp.zeros(())
    return jnp.ones(())

def prefill_tokens(x, pos):
    y = jnp.asarray(x)
    return y[:pos]
"""


def test_srv006_flags_trace_constant_geometry():
    from mxnet_tpu.analysis.serving_lint import lint_decode_trace_constants
    findings = lint_decode_trace_constants(source=_BAD_DECODE % "")
    assert len(findings) == 2
    assert all(f.rule_id == "SRV006" for f in findings)
    msgs = " ".join(f.message for f in findings)
    assert "branching" in msgs and "slice bounds" in msgs
    # the disable comment waives the branch, the slice still fires
    waived = lint_decode_trace_constants(
        source=_BAD_DECODE % "  # mxlint: disable=SRV006")
    assert len(waived) == 1 and "slice bounds" in waived[0].message


def test_srv006_shipped_decode_sources_are_clean():
    from mxnet_tpu.analysis import lint_decode_sources
    assert lint_decode_sources() == []


# -- headline: the trained LM through the fleet -------------------------------
def _train_tiny_lm(cfg, steps=10, batch=4, seed=0):
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import NDArray
    from mxnet_tpu.parallel import DataParallelTrainer, MeshPlan
    from mxnet_tpu.transformer import TransformerLM

    mx.random.seed(seed)
    trainer = DataParallelTrainer(
        TransformerLM(cfg), None, "sgd",
        {"learning_rate": 0.5, "momentum": 0.9},
        mesh_plan=MeshPlan(data=1))
    # seeded near-deterministic bigram stream: learnable structure so
    # the loss provably drops in a handful of steps
    rng = np.random.RandomState(seed + 7)
    corpus = np.zeros(2048, np.int64)
    for i in range(1, len(corpus)):
        corpus[i] = (5 * corpus[i - 1] + 1
                     + (7 if rng.rand() < 0.1 else 0)) % cfg.vocab_size
    losses = []
    for s in range(steps):
        starts = rng.randint(0, len(corpus) - cfg.seq_len - 1,
                             size=batch)
        x = np.stack([corpus[i:i + cfg.seq_len] for i in starts])
        y = np.stack([corpus[i + 1:i + 1 + cfg.seq_len] for i in starts])
        loss = trainer.step(NDArray(jnp.asarray(x)),
                            NDArray(jnp.asarray(y)))
        losses.append(float(loss.asnumpy()))
    trainer.flush()
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), \
        "tiny LM did not train: %r" % losses
    return trainer.mesh_params()


def test_e2e_trained_lm_served_through_fleet_under_burst():
    cfg = TransformerLMConfig(**CFG)
    params = _train_tiny_lm(cfg)
    prog = DecodeProgram(cfg, page_size=8)
    runner = DecodeRunner(prog, params, slots=2,
                          prefill_buckets=(8, 16, 32))

    rng = np.random.RandomState(11)
    lengths = [3, 5, 8, 11, 16, 24, 7, 12]
    prompts = [rng.randint(1, cfg.vocab_size, size=n).astype(np.int32)
               for n in lengths]
    refs = [runner.reference_decode(p, 6) for p in prompts]
    warm = runner.jit_cache_keys()

    fleet = ModelFleet()
    fleet.register_decode("lm", runner, max_queue=32,
                          token_time_hint_ms=5.0,
                          tier_slos={"gold": 250.0})
    # 8 concurrent clients over 2 slots: gold/silver served, two bronze
    # requests carry an unmeetable 1ms deadline (modeled completion
    # >= 6 tokens x 5ms hint) — shed at admission, every run
    tiers = ["gold", "silver", "gold", "silver", "bronze", "bronze",
             "gold", "silver"]
    results, sheds, errors = {}, [], []

    def client(k):
        try:
            deadline = 1 if tiers[k] == "bronze" else None
            results[k] = np.asarray(
                fleet.decode(prompts[k], model="lm", max_new_tokens=6,
                             timeout=120.0, tier=tiers[k],
                             deadline_ms=deadline), np.int32)
        except RequestShed:
            sheds.append(k)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors

    # sheds confined to bronze; everything else served token-exact
    assert sorted(sheds) == [4, 5]
    assert sorted(results) == [0, 1, 2, 3, 6, 7]
    for k, out in results.items():
        assert np.array_equal(out, refs[k]), \
            "request %d diverged from the sequential reference" % k

    st = fleet.entry("lm").batcher.stats
    assert set(st._shed_by_tier) == {"bronze"}
    assert st._shed_by_tier["bronze"] == 2
    # the declared gold SLO holds on the measured per-token latency
    p50, p99 = st.token_latency_ms("gold")
    assert 0.0 < p50 <= p99 < 250.0, (p50, p99)

    # zero steady-state recompiles, zero leaked pages
    assert runner.jit_cache_keys() == warm
    assert runner.recompiles_since_warmup() == 0
    fleet.entry("lm").batcher.drain(timeout=60.0)
    assert runner.pool.pages_in_use == 0
