"""Run-ahead dispatch engine: bulk windows, device prefetch, lazy metrics.

The engine reorders NO math — only synchronization points — so training
under any window/prefetch configuration must be bitwise-identical to the
synchronous loop (the exactness contract of ISSUE 5, mirroring the
reference engine's sequential-consistency guarantee per dependency
chain).  The HBM side: the prefetch slot ring must never hold more than
``depth`` batches, and backpressure must bound the trainer's in-flight
ring at ``engine.bulk_size()``.
"""
import threading
import time

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import engine, gluon, metric as metric_mod
from mxnet_tpu.gluon import nn
from mxnet_tpu.io import (DataBatch, NDArrayIter, DeviceFeedIter,
                          PrefetchToDeviceIter)
from mxnet_tpu.parallel import DataParallelTrainer


BATCH, FEAT, NCLS = 16, 8, 4


def _data(n=160, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, FEAT).astype(np.float32)
    y = (np.arange(n) % NCLS).astype(np.float32)
    return X, y


def _trainer(lr=0.1, momentum=0.9):
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(NCLS))
    net.initialize(mx.init.Xavier())
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": lr,
                                     "momentum": momentum})
    return net, tr


def _run_steps(mode, nsteps=10):
    """10 fixed steps under a dispatch mode; returns (losses, params)."""
    X, y = _data()
    net, tr = _trainer()
    xb, yb = mx.nd.array(X[:BATCH]), mx.nd.array(y[:BATCH])
    losses = []
    if mode == "bulk":
        with engine.bulk(4) as prev:
            assert isinstance(prev, int) and prev >= 1
            for _ in range(nsteps):
                losses.append(tr.step(xb, yb))
    else:
        prev = engine.set_bulk_size(mode)
        try:
            for _ in range(nsteps):
                losses.append(tr.step(xb, yb))
        finally:
            engine.set_bulk_size(prev)
            engine.flush()
    params = [v.data().asnumpy()
              for v in net.collect_params().values()]
    return [float(l.asscalar()) for l in losses], params


# ---------------------------------------------------------------------------
# engine surface
# ---------------------------------------------------------------------------
def test_set_bulk_size_returns_prev_and_validates():
    prev = engine.set_bulk_size(3)
    try:
        assert engine.bulk_size() == 3
        assert engine.set_bulk_size(5) == 3
        with pytest.raises(ValueError):
            engine.set_bulk_size(0)
    finally:
        engine.set_bulk_size(prev)


def test_bulk_yields_prev_and_restores_on_exception():
    base = engine.bulk_size()
    with engine.bulk(7) as prev:
        assert prev == base
        assert engine.bulk_size() == 7
    assert engine.bulk_size() == base
    # the exit path must restore + flush even when the body raises
    with pytest.raises(RuntimeError):
        with engine.bulk(3):
            assert engine.bulk_size() == 3
            raise RuntimeError("boom")
    assert engine.bulk_size() == base


def test_flush_drains_registered_ring():
    drained = []

    class Ring:
        def flush(self):
            drained.append(True)

    r = Ring()
    engine.register_flusher(r.flush)
    engine.flush()
    assert drained
    # weakly held: a dropped component unregisters itself
    del r
    n = len(drained)
    engine.flush()
    assert len(drained) == n


# ---------------------------------------------------------------------------
# exactness: run-ahead must not change a single bit
# ---------------------------------------------------------------------------
def test_runahead_bitwise_identical_depth_1_vs_4_vs_bulk():
    l1, p1 = _run_steps(1)
    l4, p4 = _run_steps(4)
    lb, pb = _run_steps("bulk")
    assert l1 == l4 == lb
    for a, b in zip(p1, p4):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(p1, pb):
        np.testing.assert_array_equal(a, b)


def test_fit_prefetch_bitwise_matches_step_loop():
    """fit (prefetch + bulk + lazy metric) == the plain step loop."""
    X, y = _data()

    def by_fit():
        net, tr = _trainer()
        m = tr.fit(NDArrayIter(X, y, BATCH, last_batch_handle="discard"),
                   num_epoch=1, bulk_size=4)
        return (m.get()[1],
                [v.data().asnumpy() for v in net.collect_params().values()])

    def by_steps():
        net, tr = _trainer()
        tot, n = None, 0
        for s in range(0, len(X), BATCH):
            loss = tr.step(mx.nd.array(X[s:s + BATCH]),
                           mx.nd.array(y[s:s + BATCH]))
            tot = loss if tot is None else tot + loss
            n += 1
        tr.flush()
        return (float(tot.asscalar()) / n,
                [v.data().asnumpy() for v in net.collect_params().values()])

    v_fit, p_fit = by_fit()
    v_ref, p_ref = by_steps()
    assert v_fit == pytest.approx(v_ref, rel=1e-6)
    for a, b in zip(p_fit, p_ref):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# HBM bound: the prefetch slot ring
# ---------------------------------------------------------------------------
def test_prefetch_ring_bounds_live_batches():
    X, y = _data(n=12 * BATCH)

    produced = []

    class Counting(NDArrayIter):
        def next(self):
            b = super().next()
            produced.append(1)
            return b

    base = Counting(X, y, BATCH, last_batch_handle="discard")
    depth = 2
    pf = PrefetchToDeviceIter(base, depth=depth)
    consumed = 0
    overdraft = 0
    for b in pf:
        # give the worker every chance to run ahead; the ring must stop it
        time.sleep(0.01)
        consumed += 1
        # the worker may hold one batch it pulled from base but whose slot
        # it acquired before transferring — produced-vs-consumed can lead
        # by at most the ring depth + that one in-hand batch
        overdraft = max(overdraft, len(produced) - consumed)
    assert consumed == 12
    assert pf.live_slots_max <= depth, pf.live_slots_max
    assert overdraft <= depth + 1, overdraft


def test_prefetch_hbm_bound_reported():
    X, y = _data()
    pf = PrefetchToDeviceIter(NDArrayIter(X, y, BATCH), depth=3)
    per_batch = BATCH * FEAT * 4 + BATCH * 4  # f32 data + f32 labels
    assert pf.batch_bytes() == per_batch
    assert pf.hbm_bound_bytes() == 3 * per_batch
    list(pf)  # drain so the worker thread exits cleanly


def test_prefetch_sharded_batches_hit_step_fast_path(monkeypatch):
    """Batches prefetched onto the trainer's batch_sharding are used
    as-is by step() — no second device_put of the batch."""
    X, y = _data()
    net, tr = _trainer()
    # prime setup with a host batch (this one IS put by the trainer)
    tr.step(mx.nd.array(X[:BATCH]), mx.nd.array(y[:BATCH]))

    xs = jax.device_put(X[:BATCH], tr.batch_sharding)
    ys = jax.device_put(y[:BATCH], tr.batch_sharding)
    assert tr._put_batch(xs, tr.batch_sharding) is xs

    calls = []
    real_put = jax.device_put

    def spy(x, *a, **k):
        calls.append(x)
        return real_put(x, *a, **k)

    monkeypatch.setattr(jax, "device_put", spy)
    tr.step(mx.nd.NDArray(xs), mx.nd.NDArray(ys))
    assert not any(x is xs or x is ys for x in calls), \
        "committed sharded batch was re-put"
    tr.flush()


# ---------------------------------------------------------------------------
# backpressure
# ---------------------------------------------------------------------------
def test_backpressure_bounds_inflight_ring():
    X, y = _data()
    net, tr = _trainer()
    xb, yb = mx.nd.array(X[:BATCH]), mx.nd.array(y[:BATCH])
    prev = engine.set_bulk_size(2)
    try:
        for _ in range(12):
            tr.step(xb, yb)
            assert len(tr._inflight) <= 2
    finally:
        engine.set_bulk_size(prev)
        engine.flush()
    assert not tr._inflight  # flush drained the ring
    snap = tr.dispatch_stats.snapshot()
    assert snap["dispatched_steps"] == 12
    assert 1 <= snap["inflight_max"] <= 2
    assert snap["dispatch_stall_s"] >= 0.0


def test_backpressure_under_slow_step_keeps_window_full():
    """With a step much slower than dispatch, the ring sits AT the window
    (the device queue stays full) and never beyond it."""
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(512, activation="relu"), nn.Dense(512))
    net.initialize(mx.init.Xavier())
    tr = DataParallelTrainer(net, gluon.loss.L2Loss(), "sgd",
                             {"learning_rate": 0.01})
    rng = np.random.RandomState(0)
    xb = mx.nd.array(rng.rand(64, 256).astype(np.float32))
    yb = mx.nd.array(rng.rand(64, 512).astype(np.float32))
    with engine.bulk(3):
        for _ in range(8):
            tr.step(xb, yb)
            assert len(tr._inflight) <= 3
    assert tr.dispatch_stats.snapshot()["inflight_max"] == 3


# ---------------------------------------------------------------------------
# lazy metrics
# ---------------------------------------------------------------------------
def test_lazy_metric_values_identical():
    rng = np.random.RandomState(3)
    labels = [mx.nd.array((rng.rand(8) * NCLS).astype(np.float32) // 1)
              for _ in range(5)]
    preds = [mx.nd.array(rng.rand(8, NCLS).astype(np.float32))
             for _ in range(5)]
    for name in ("acc", "mse", "loss"):
        eager = metric_mod.create(name)
        lazy = metric_mod.create(name)
        for l, p in zip(labels, preds):
            pl = p if name != "mse" else mx.nd.array(
                np.asarray([[float(v)] for v in l.asnumpy()]))
            eager.update([l], [pl])
            lazy.update_lazy([l], [pl])
        assert eager.get() == lazy.get()


def test_lazy_metric_drains_at_reads_and_bounds_pending():
    m = metric_mod.create("loss")
    x = mx.nd.array(np.ones(4, np.float32))
    for _ in range(3):
        m.update_lazy([], [x])
    assert len(m._lazy) == 3 and m.num_inst == 0  # parked, not fetched
    name, val = m.get()
    assert not m._lazy and val == 1.0
    # the pending window is bounded: old entries auto-drain
    for _ in range(m.LAZY_MAX_PENDING + 10):
        m.update_lazy([], [x])
    assert len(m._lazy) <= m.LAZY_MAX_PENDING
    m.reset()
    assert m._lazy == [] and m.get()[1] != m.get()[1]  # nan after reset


def test_module_fit_lazy_metric_matches_eager(tmp_path):
    """Module.fit with the lazy update path reports the same epoch metric
    as an eager re-evaluation of the same updates."""
    X, y = _data(n=8 * BATCH)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=NCLS)
    sym = mx.sym.SoftmaxOutput(fc, name="softmax")

    def fit_once(lazy):
        mx.random.seed(5)
        mod = mx.mod.Module(sym)
        it = NDArrayIter(X, y, BATCH, last_batch_handle="discard")
        mod.bind(it.provide_data, it.provide_label)
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        m = metric_mod.create("acc")
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(m, batch.label, lazy=lazy)
        return m.get()

    assert fit_once(True) == fit_once(False)


# ---------------------------------------------------------------------------
# crash consistency
# ---------------------------------------------------------------------------
def test_interrupt_inside_bulk_leaves_params_consistent():
    """KeyboardInterrupt mid-window: bulk's exit flush still runs, every
    dispatched step completes, and params equal a clean run of the same
    number of steps — nothing is torn by donation."""
    X, y = _data()
    xb_np, yb_np = X[:BATCH], y[:BATCH]

    def clean(nsteps):
        net, tr = _trainer()
        for _ in range(nsteps):
            tr.step(mx.nd.array(xb_np), mx.nd.array(yb_np))
        tr.flush()
        return [v.data().asnumpy() for v in net.collect_params().values()]

    net, tr = _trainer()
    with pytest.raises(KeyboardInterrupt):
        with engine.bulk(4):
            for i in range(10):
                tr.step(mx.nd.array(xb_np), mx.nd.array(yb_np))
                if i == 5:
                    raise KeyboardInterrupt
    assert not tr._inflight  # the exit flush drained the ring
    got = [v.data().asnumpy() for v in net.collect_params().values()]
    want = clean(6)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    # and the trainer keeps working after the interrupt
    after = tr.step(mx.nd.array(xb_np), mx.nd.array(yb_np))
    assert np.isfinite(float(after.asscalar()))


# ---------------------------------------------------------------------------
# DeviceFeedIter stats surface (acceptance: stall counters visible)
# ---------------------------------------------------------------------------
def test_device_feed_stats_and_dispatch_counters_shape():
    X, y = _data()
    it = DeviceFeedIter(NDArrayIter(X, y, BATCH), depth=2)
    list(it)
    snap = it.stats.snapshot()
    for key in ("batches", "stall_s", "queue_depth_max",
                "dispatched_steps", "inflight_max", "dispatch_stall_s"):
        assert key in snap
    assert snap["batches"] == len(X) // BATCH


def test_trainer_fit_decreases_loss_with_speedometer():
    X, y = _data(n=20 * BATCH, seed=2)
    net, tr = _trainer(lr=0.5)
    ticks = []

    def cb(param):
        # Speedometer-style flush boundary: reading the metric drains it
        if param.nbatch % 5 == 0:
            ticks.append(param.eval_metric.get()[1])

    m = tr.fit(NDArrayIter(X, y, BATCH, last_batch_handle="discard"),
               num_epoch=3, bulk_size=4, batch_end_callback=cb)
    assert ticks and ticks[-1] < ticks[0]
