"""Multi-process input pipeline + fused device tail (PR 3).

Reference: the C++ ImageRecordIter's preprocess_threads decode team +
prefetcher (src/io/iter_image_recordio_2.cc, iter_prefetcher.h); here the
contracts under test are the pipeline's own: bitwise multi-process /
in-process equivalence under a fixed seed, exactly-once delivery across a
worker crash, bounded memory under a slow consumer, and a uint8-fed train
step that matches the float-fed one with zero added steady-state
recompiles.
"""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.io.device_tail import make_device_tail, tail_cache_sizes
from mxnet_tpu.io.pipeline import ImagePipelineIter, pipeline_available

cv2 = pytest.importorskip("cv2")

pytestmark = pytest.mark.skipif(not pipeline_available(),
                                reason="no multiprocessing shared memory")


def _make_rec(tmp_path, n=24, size=32):
    rng = np.random.RandomState(0)
    rec = str(tmp_path / "p.rec")
    idx = str(tmp_path / "p.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=95))
    w.close()
    return rec, idx


def _drain(it):
    return [(b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad) for b in it]


_KW = dict(batch_size=4, data_shape=(3, 28, 28), rand_crop=True,
           rand_mirror=True, brightness=0.2, native_decode=False)


def test_pipeline_mp_matches_inprocess_bitwise(tmp_path):
    """The core determinism contract: same seed -> bitwise-identical
    stream for any worker count, across epochs."""
    rec, idx = _make_rec(tmp_path)
    it0 = ImagePipelineIter(num_workers=0, seed=7, shuffle=True,
                            path_imgrec=rec, path_imgidx=idx, **_KW)
    it2 = ImagePipelineIter(num_workers=2, seed=7, shuffle=True,
                            path_imgrec=rec, path_imgidx=idx, **_KW)
    try:
        ref, got = _drain(it0), _drain(it2)
        assert len(ref) == len(got) == 6
        for (d0, l0, p0), (d1, l1, p1) in zip(ref, got):
            assert np.array_equal(d0, d1)
            assert np.array_equal(l0, l1)
            assert p0 == p1
        # epoch 2: reshuffled (different from epoch 1) but still identical
        # between the two pipelines
        it0.reset()
        it2.reset()
        ref2, got2 = _drain(it0), _drain(it2)
        for (d0, l0, _), (d1, l1, _) in zip(ref2, got2):
            assert np.array_equal(d0, d1)
            assert np.array_equal(l0, l1)
        assert not all(np.array_equal(a[1], b[1])
                       for a, b in zip(ref, ref2))
    finally:
        it2.close()


def test_pipeline_worker_crash_respawns_exactly_once(tmp_path):
    """SIGKILL a worker mid-epoch: it is respawned, its undelivered
    batches are re-dispatched, and no batch is dropped or duplicated."""
    rec, idx = _make_rec(tmp_path, n=32)
    it = ImagePipelineIter(num_workers=2, seed=3, shuffle=False,
                           path_imgrec=rec, path_imgidx=idx, **_KW)
    try:
        first = it.next()
        it._procs[0].kill()
        rest = []
        while True:
            try:
                rest.append(it.next())
            except StopIteration:
                break
        labels = np.concatenate([first.label[0].asnumpy()]
                                + [b.label[0].asnumpy() for b in rest])
        assert sorted(labels.tolist()) == [float(i) for i in range(32)]
        assert it.stats.snapshot()["respawns"] >= 1
    finally:
        it.close()


def test_pipeline_backpressure_bounded(tmp_path):
    """A slow consumer must bound the pipeline, not grow it: at most
    depth slots per worker are ever in flight or buffered."""
    rec, idx = _make_rec(tmp_path, n=32)
    depth = 2
    it = ImagePipelineIter(num_workers=1, prefetch_buffer=depth, seed=1,
                           shuffle=False, path_imgrec=rec, path_imgidx=idx,
                           **_KW)
    try:
        # let the worker run ahead as far as it can, then consume slowly
        time.sleep(1.5)
        seen = 0
        for _ in it:
            seen += 1
            time.sleep(0.05)
        assert seen == 8
        snap = it.stats.snapshot()
        # the reorder buffer (host copies) is bounded by the dispatch
        # throttle: at most ~2x the slot budget even under a slow
        # consumer — never proportional to the epoch
        assert snap["queue_depth_max"] <= 2 * (1 * depth)
        assert snap["batches"] == 8
    finally:
        it.close()


def test_pipeline_reset_midepoch_no_leak(tmp_path):
    """reset() before exhaustion: stale deliveries are dropped by epoch
    tag and the next epoch still yields every batch exactly once."""
    rec, idx = _make_rec(tmp_path, n=24)
    it = ImagePipelineIter(num_workers=2, seed=5, shuffle=False,
                           path_imgrec=rec, path_imgidx=idx, **_KW)
    try:
        it.next()
        it.reset()
        labels = np.concatenate([b.label[0].asnumpy() for b in it])
        assert sorted(labels.tolist()) == [float(i) for i in range(24)]
    finally:
        it.close()


def test_image_record_iter_honors_knobs(tmp_path):
    """prefetch_buffer reaches the ring depth / prefetch queue and
    preprocess_threads maps to worker-process count (not GIL threads)."""
    rec, idx = _make_rec(tmp_path)
    it = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                               batch_size=4, data_shape=(3, 28, 28),
                               preprocess_threads=2, prefetch_buffer=3,
                               seed=0)
    try:
        assert isinstance(it, ImagePipelineIter)
        assert it._n_workers == 2 and it._depth == 3
        assert len(it._procs) == 2
        b = it.next()
        assert b.data[0].shape == (4, 3, 28, 28)
    finally:
        it.close()
    # workers=0, no seed: thread prefetch with the requested queue depth
    it2 = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                                batch_size=4, data_shape=(3, 28, 28),
                                prefetch_buffer=3)
    assert isinstance(it2, mx.io.PrefetchingIter)
    assert it2._queue.maxsize == 3


def test_image_det_record_iter_warns_once(tmp_path):
    """ImageDetRecordIter no longer silently eats preprocess_threads."""
    import warnings as _w
    from mxnet_tpu.io import _WARNED
    _WARNED.clear()
    rec, idx = _make_rec(tmp_path)  # plain labels: header flag 0
    with _w.catch_warnings(record=True) as caught:
        _w.simplefilter("always")
        try:
            mx.io.ImageDetRecordIter(path_imgrec=rec, path_imgidx=idx,
                                     batch_size=4, data_shape=(3, 28, 28),
                                     preprocess_threads=2)
        except Exception:
            pass  # det labels absent; only the warning matters here
    assert any("preprocess_threads" in str(w.message) for w in caught)


def test_device_tail_recompile_free_and_shared():
    """One tail per (mean, std, dtype, layout) config, one XLA trace per
    geometry across many batches and iterators — the zero-recompile proof
    via the jit-cache hooks."""
    mean = np.array([1.0, 2.0, 3.0], np.float32)
    std = np.array([4.0, 5.0, 6.0], np.float32)
    tail = make_device_tail(mean, std, dtype="float32", layout="NCHW")
    assert make_device_tail(mean, std, dtype="float32",
                            layout="NCHW") is tail
    rng = np.random.RandomState(0)
    u8 = rng.randint(0, 255, (40, 8, 8, 3), np.uint8)
    it = mx.io.NDArrayIter(u8, np.zeros(40, np.float32), 8)
    feed = mx.io.DeviceFeedIter(it, transform=tail)
    outs = [b.data[0] for b in feed]
    assert len(outs) == 5
    assert outs[0].shape == (8, 3, 8, 8)
    assert tail_cache_sizes()[tail.tail_key] == 1
    # numerics: same math as the host float path
    want = ((u8[:8].astype(np.float32) - mean) / std).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(outs[0].asnumpy(), want, rtol=1e-6,
                               atol=1e-5)


def test_uint8_fed_step_matches_float_fed():
    """One train step fed raw uint8 through the in-step fused tail equals
    the float-fed host-normalized step, and the uint8 signature adds no
    steady-state recompiles."""
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DataParallelTrainer

    mean = np.array([120.0, 115.0, 100.0], np.float32)
    std = np.array([58.0, 57.0, 56.0], np.float32)
    tail = make_device_tail(mean, std, dtype="float32", layout="NHWC")
    rng = np.random.RandomState(0)
    u8 = rng.randint(0, 255, (8, 12, 12, 3), np.uint8)
    host = (u8.astype(np.float32) - mean) / std
    y = mx.nd.array((rng.rand(8) * 4).astype(np.int64))

    def build():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, layout="NHWC"),
                gluon.nn.GlobalAvgPool2D(layout="NHWC"),
                gluon.nn.Flatten(), gluon.nn.Dense(4))
        net.initialize(mx.init.Xavier())
        return net

    netA, netB = build(), build()
    netA(mx.nd.array(host[:1]))
    netB(mx.nd.array(host[:1]))
    for pA, pB in zip(netA.collect_params().values(),
                      netB.collect_params().values()):
        pA.set_data(mx.nd.array(pB.data().asnumpy()))
    tA = DataParallelTrainer(netA, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": 0.1},
                             input_transform=tail)
    tB = DataParallelTrainer(netB, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": 0.1})
    lA = tA.step(mx.nd.array(u8, dtype=np.uint8), y).asscalar()
    lB = tB.step(mx.nd.array(host), y).asscalar()
    np.testing.assert_allclose(lA, lB, rtol=1e-5, atol=1e-6)
    for pA, pB in zip(netA.collect_params().values(),
                      netB.collect_params().values()):
        np.testing.assert_allclose(pA.data().asnumpy(),
                                   pB.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6)
    # steady state: more uint8 steps, still one compiled step program
    before = tA._step_fn._cache_size()
    for _ in range(3):
        tA.step(mx.nd.array(u8, dtype=np.uint8), y)
    assert tA._step_fn._cache_size() == before == 1


def test_executor_feed_dtype_stable():
    """Feeding a float-bound executor a uint8 (or other-width float)
    batch keeps the jit signature fixed: the feed is cast on device
    instead of retracing the program."""
    import mxnet_tpu.symbol as sym
    data = sym.var("data")
    out = sym.FullyConnected(data, num_hidden=3, name="feedcast_fc")
    ex = out.simple_bind(mx.cpu(), data=(4, 6))
    ex.forward(is_train=False,
               data=mx.nd.array(np.ones((4, 6), np.float32)))
    keys0 = ex.jit_cache_keys()
    ex.forward(is_train=False,
               data=mx.nd.array(np.ones((4, 6), np.uint8), dtype=np.uint8))
    ex.forward(is_train=False,
               data=mx.nd.array(np.ones((4, 6)), dtype="bfloat16"))
    assert ex.jit_cache_keys() == keys0


def test_recordio_read_at_positional(tmp_path):
    rec = str(tmp_path / "r.rec")
    w = recordio.MXRecordIO(rec, "w")
    offs = []
    for i in range(5):
        offs.append(w.tell())
        w.write(b"payload-%d" % i)
    w.close()
    r = recordio.MXRecordIO(rec, "r")
    # positional reads in arbitrary order never disturb the cursor
    assert r.read_at(offs[3]) == b"payload-3"
    assert r.read() == b"payload-0"
    assert r.read_at(offs[1]) == b"payload-1"
    assert r.read() == b"payload-1"
    r.close()


def test_pipeline_stats_shape(tmp_path):
    rec, idx = _make_rec(tmp_path, n=8)
    it = ImagePipelineIter(num_workers=1, seed=0, shuffle=False,
                           path_imgrec=rec, path_imgidx=idx, **_KW)
    try:
        list(it)
        snap = it.stats.snapshot()
        for key in ("batches", "worker_utilization", "stall_pct",
                    "queue_depth_max", "respawns", "wall_s"):
            assert key in snap
        assert snap["batches"] == 2 and snap["respawns"] == 0
    finally:
        it.close()
