"""Fault-tolerant elastic training (ISSUE 6): deterministic chaos harness,
auto-checkpoint/resume, heartbeat kvstore tier.

The acceptance contracts under test:
- chaos schedules are seeded-deterministic and replay exactly;
- checkpoints are atomic under kill-during-save (the previous snapshot
  survives a SIGKILL mid-write);
- crash + resume converges *bitwise-identically* to the uncrashed run at
  the same step count — in-process (trainer-level) and end-to-end (a
  subprocess SIGKILLed mid-epoch by the chaos harness, then resumed);
- a SIGKILLed pipeline worker costs nothing (exactly-once), but a
  deterministic crasher trips ``PipelineWorkerStorm`` instead of
  respawn-looping;
- the PS heartbeat watchdog declares silent workers dead and reassigns
  their keys; the bounded-staleness gate refuses lagging rejoiners
  (deleting either mechanism fails these tests — the gate bites);
- serving splits liveness from readiness and drain honors its deadline;
- SRC005 flags unbounded blocking calls in while-loops and the shipped
  worker loops are clean.
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, kvstore_ps
from mxnet_tpu.parallel import DataParallelTrainer
from mxnet_tpu.resilience import (BackoffPolicy, ChaosSchedule, Fault,
                                  RetriesExhausted, chaos,
                                  checkpoint as ckpt, retry_call)
from mxnet_tpu.resilience.heartbeat import HeartbeatMonitor

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _no_chaos_leak():
    yield
    chaos.uninstall()


def _cpu_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 local device is enough for children
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


# ---------------------------------------------------------------------------
# backoff policy
# ---------------------------------------------------------------------------
def test_backoff_deterministic_bounded_and_growing():
    a = BackoffPolicy(base_s=0.5, factor=2.0, max_delay_s=4.0,
                      max_retries=6, jitter=0.25, seed=7)
    b = BackoffPolicy(base_s=0.5, factor=2.0, max_delay_s=4.0,
                      max_retries=6, jitter=0.25, seed=7)
    da, db = a.delays(), b.delays()
    assert da == db                       # seeded jitter replays exactly
    for i, d in enumerate(da):
        lo = min(0.5 * 2.0 ** i, 4.0) * 0.75
        hi = min(0.5 * 2.0 ** i, 4.0) * 1.25
        assert lo <= d <= hi
    # different seed, different jitter stream
    c = BackoffPolicy(base_s=0.5, factor=2.0, max_delay_s=4.0,
                      max_retries=6, jitter=0.25, seed=8)
    assert c.delays() != da
    # no jitter: exact exponential, capped
    p = BackoffPolicy(base_s=1.0, factor=3.0, max_delay_s=5.0,
                      max_retries=4, jitter=0.0)
    assert p.delays() == [1.0, 3.0, 5.0, 5.0]


def test_retry_call_succeeds_then_exhausts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("nope")
        return "ok"

    pol = BackoffPolicy(base_s=0.001, max_retries=5, jitter=0.0)
    assert retry_call(flaky, policy=pol) == "ok"
    assert len(calls) == 3

    def always():
        raise OSError("down")

    with pytest.raises(RetriesExhausted):
        retry_call(always, policy=BackoffPolicy(base_s=0.001, max_retries=2,
                                                jitter=0.0))


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------
def test_chaos_schedule_seeded_deterministic():
    s1 = ChaosSchedule.seeded(11, ["a", "b"], n_faults=5, max_at=20)
    s2 = ChaosSchedule.seeded(11, ["a", "b"], n_faults=5, max_at=20)
    assert s1.specs() == s2.specs()
    assert s1.specs() != ChaosSchedule.seeded(12, ["a", "b"],
                                              n_faults=5, max_at=20).specs()


def test_chaos_raise_delay_and_counts():
    chaos.install([Fault("rpc", 3, "raise"),
                   Fault("rpc", 5, "delay", 0.05)])
    chaos.maybe_inject("rpc")
    chaos.maybe_inject("rpc")
    with pytest.raises(chaos.ChaosError):
        chaos.maybe_inject("rpc")          # hit 3
    chaos.maybe_inject("rpc")              # hit 4: clean
    t0 = time.perf_counter()
    chaos.maybe_inject("rpc")              # hit 5: stalled
    assert time.perf_counter() - t0 >= 0.04
    assert [t[:2] for t in chaos.triggered()] == [("rpc", 3), ("rpc", 5)]
    chaos.uninstall()
    chaos.maybe_inject("rpc")              # inactive: free no-op


def test_chaos_env_spec_parses():
    os.environ["MXTPU_CHAOS"] = "trainer.step:7:kill,rpc:2:delay:0.1"
    try:
        sched = chaos.install_from_env()
        assert sched.specs()[0][:3] == ("trainer.step", 7, "kill")
        assert sched.specs()[1] == ("rpc", 2, "delay", 0.1)
    finally:
        del os.environ["MXTPU_CHAOS"]
        chaos.uninstall()


# ---------------------------------------------------------------------------
# checkpoint atomicity
# ---------------------------------------------------------------------------
def test_checkpoint_roundtrip_prune_and_latest(tmp_path):
    d = str(tmp_path)
    for step in (1, 2, 3, 4):
        ckpt.save_checkpoint(
            d, {"w": ckpt.encode_array(np.full(3, step, np.float32))},
            step=step, keep=2)
    steps = [s for s, _ in ckpt.list_checkpoints(d)]
    assert steps == [3, 4]                 # pruned to keep=2
    path, rec = ckpt.latest_checkpoint(d)
    assert rec["step"] == 4
    np.testing.assert_array_equal(ckpt.decode_array(rec["payload"]["w"]),
                                  np.full(3, 4, np.float32))
    # bf16 survives the byte round-trip exactly
    import jax.numpy as jnp
    x = jnp.arange(5, dtype=jnp.bfloat16) / 3
    back = ckpt.decode_array(ckpt.encode_array(x))
    assert str(back.dtype) == "bfloat16"
    assert np.asarray(x).tobytes() == back.tobytes()


def test_checkpoint_kill_during_save_keeps_previous(tmp_path):
    """SIGKILL mid-save (chaos site checkpoint.save): the torn snapshot
    must never appear; the previous one stays the loadable latest."""
    d = str(tmp_path)
    script = (
        "import sys, numpy as np\n"
        "from mxnet_tpu.resilience import checkpoint as ck, chaos\n"
        "d = sys.argv[1]\n"
        "ck.save_checkpoint(d, {'w': ck.encode_array(np.arange(4.))},"
        " step=1)\n"
        "print('SAVED1', flush=True)\n"
        "chaos.install([chaos.Fault('checkpoint.save', 1, 'kill')])\n"
        "ck.save_checkpoint(d, {'w': ck.encode_array(np.zeros(4))},"
        " step=2)\n"
        "print('UNREACHABLE', flush=True)\n")
    out = subprocess.run([sys.executable, "-c", script, d], env=_cpu_env(),
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == -signal.SIGKILL, (out.returncode, out.stderr)
    assert "SAVED1" in out.stdout and "UNREACHABLE" not in out.stdout
    path, rec = ckpt.latest_checkpoint(d)
    assert rec["step"] == 1                # step-2 never materialized
    np.testing.assert_array_equal(ckpt.decode_array(rec["payload"]["w"]),
                                  np.arange(4.0))
    # the crashed save's tmp debris is pruned by the next good save
    ckpt.save_checkpoint(d, {"w": ckpt.encode_array(np.ones(2))}, step=3)
    assert not [n for n in os.listdir(d) if ".tmp." in n]


# ---------------------------------------------------------------------------
# trainer checkpoint/resume — bitwise identity
# ---------------------------------------------------------------------------
def _mlp_trainer(seed):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9})


def _params_blob(tr):
    return b"".join(np.asarray(p.data()._data).tobytes()
                    for _, p in sorted(tr._params_by_name.items()))


def _batches(n, batch=8, feat=12, seed=42):
    rng = np.random.RandomState(seed)
    return [(mx.nd.array(rng.rand(batch, feat).astype(np.float32)),
             mx.nd.array(rng.randint(0, 4, batch).astype(np.int64)))
            for _ in range(n)]


def test_trainer_resume_bitwise_identical(tmp_path):
    data = _batches(8)
    ref = _mlp_trainer(5)
    for x, y in data:
        ref.step(x, y)
    ref.flush()
    blob_ref = _params_blob(ref)

    crash = _mlp_trainer(5)
    for x, y in data[:4]:
        crash.step(x, y)
    crash.save_checkpoint(str(tmp_path), epoch=0, nbatch=3)

    cont = _mlp_trainer(99)     # wrong seed on purpose: restore must win
    cursor = cont.restore_checkpoint(str(tmp_path))
    assert cursor["step"] == 4 and cursor["nbatch"] == 3
    for x, y in data[4:]:
        cont.step(x, y)
    cont.flush()
    assert _params_blob(cont) == blob_ref
    # optimizer momentum state restored too (not just params)
    import jax
    sref = jax.tree_util.tree_leaves(ref._states_raw)
    scon = jax.tree_util.tree_leaves(cont._states_raw)
    assert len(sref) == len(scon)
    for a, b in zip(sref, scon):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_fit_auto_checkpoint_and_resume(tmp_path):
    rng = np.random.RandomState(3)
    X = rng.rand(48, 12).astype(np.float32)
    Y = rng.randint(0, 4, 48).astype(np.int64)

    def make_iter():
        return mx.io.NDArrayIter(X, Y, batch_size=8)

    ref = _mlp_trainer(21)
    ref.fit(make_iter(), num_epoch=2, bulk_size=4)
    blob_ref = _params_blob(ref)

    # "crash" after epoch 0 (checkpoints were written), then resume in a
    # fresh trainer: epoch 1 replays to the identical end state
    part = _mlp_trainer(21)
    part.fit(make_iter(), num_epoch=1, bulk_size=4,
             checkpoint_dir=str(tmp_path), checkpoint_every=2)
    assert ckpt.list_checkpoints(str(tmp_path))

    cont = _mlp_trainer(77)
    cont.fit(make_iter(), num_epoch=2, bulk_size=4,
             checkpoint_dir=str(tmp_path), checkpoint_every=2, resume=True)
    assert cont._step_count == 12
    assert _params_blob(cont) == blob_ref


_CRASH_SCRIPT = """
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.parallel import DataParallelTrainer
from mxnet_tpu.resilience import chaos
chaos.install_from_env()
ckdir, outpath = sys.argv[1], sys.argv[2]
mx.random.seed(5); np.random.seed(5)
rng = np.random.RandomState(42)
X = rng.rand(48, 16).astype(np.float32)
Y = rng.randint(0, 4, 48).astype(np.int64)
it = mx.io.NDArrayIter(X, Y, batch_size=8)
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(32, activation='relu'))
net.add(gluon.nn.Dense(4))
net.initialize(mx.init.Xavier())
tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), 'sgd',
                         {'learning_rate': 0.1, 'momentum': 0.9})
tr.fit(it, num_epoch=3, bulk_size=4, checkpoint_dir=ckdir,
       checkpoint_every=2, resume=True)
blob = b''.join(np.asarray(p.data()._data).tobytes()
                for _, p in sorted(tr._params_by_name.items()))
with open(outpath, 'wb') as f:
    f.write(blob)
print('DONE', tr._step_count, flush=True)
"""


def test_sigkill_mid_epoch_resume_end_to_end(tmp_path):
    """The headline acceptance test: SIGKILL the training process
    mid-epoch (chaos, step 8 of 18), resume from the auto-checkpoint in
    a fresh process, and final params are bitwise-identical to the
    fault-free run at the same step count."""
    env = _cpu_env()
    ref_out = str(tmp_path / "ref.bin")
    out = subprocess.run(
        [sys.executable, "-c", _CRASH_SCRIPT, str(tmp_path / "ref_ck"),
         ref_out], env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "DONE 18" in out.stdout

    crash_env = dict(env, MXTPU_CHAOS="trainer.step:8:kill")
    res_out = str(tmp_path / "res.bin")
    out = subprocess.run(
        [sys.executable, "-c", _CRASH_SCRIPT, str(tmp_path / "ck"),
         res_out], env=crash_env, capture_output=True, text=True,
        timeout=300)
    assert out.returncode == -signal.SIGKILL, (out.returncode,
                                               out.stderr[-2000:])
    assert ckpt.list_checkpoints(str(tmp_path / "ck"))
    assert not os.path.exists(res_out)

    out = subprocess.run(
        [sys.executable, "-c", _CRASH_SCRIPT, str(tmp_path / "ck"),
         res_out], env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "DONE 18" in out.stdout
    with open(ref_out, "rb") as f:
        ref = f.read()
    with open(res_out, "rb") as f:
        res = f.read()
    assert ref == res


# ---------------------------------------------------------------------------
# pipeline chaos: worker kill (exactly-once) and worker storm
# ---------------------------------------------------------------------------
def _pipeline_deps():
    pytest.importorskip("cv2")
    from mxnet_tpu.io.pipeline import pipeline_available
    if not pipeline_available():
        pytest.skip("no multiprocessing shared memory")


def _make_rec(tmp_path, n=32, size=32):
    from mxnet_tpu import recordio
    rng = np.random.RandomState(0)
    rec = str(tmp_path / "p.rec")
    idx = str(tmp_path / "p.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=95))
    w.close()
    return rec, idx


_PIPE_KW = dict(batch_size=4, data_shape=(3, 28, 28), native_decode=False)


def test_chaos_kills_pipeline_worker_exactly_once(tmp_path):
    """A chaos-scheduled SIGKILL of a pipeline worker at dispatch #3:
    the stream is still complete and in order (exactly-once), and the
    respawn shows up in the stats."""
    _pipeline_deps()
    from mxnet_tpu.io.pipeline import ImagePipelineIter
    rec, idx = _make_rec(tmp_path)

    it0 = ImagePipelineIter(num_workers=0, seed=2, shuffle=False,
                            path_imgrec=rec, path_imgidx=idx, **_PIPE_KW)
    ref = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it0]

    chaos.install([Fault("pipeline.dispatch", 3, "call",
                         lambda ctx: ctx[0]._procs[ctx[1]].kill())])
    it = ImagePipelineIter(num_workers=2, seed=2, shuffle=False,
                           path_imgrec=rec, path_imgidx=idx, **_PIPE_KW)
    try:
        got = [(b.data[0].asnumpy(), b.label[0].asnumpy()) for b in it]
        assert chaos.triggered()           # the kill really happened
        assert len(got) == len(ref)
        for (d0, l0), (d1, l1) in zip(ref, got):
            assert np.array_equal(d0, d1) and np.array_equal(l0, l1)
        assert it.stats.snapshot()["respawns"] >= 1
    finally:
        it.close()
        chaos.uninstall()


def test_pipeline_worker_storm_raises(tmp_path):
    """A deterministic crasher must trip PipelineWorkerStorm after
    max_respawns deaths in one epoch, not respawn-loop forever."""
    _pipeline_deps()
    from mxnet_tpu.io.pipeline import ImagePipelineIter, PipelineWorkerStorm
    rec, idx = _make_rec(tmp_path)
    it = ImagePipelineIter(num_workers=1, max_respawns=1, seed=1,
                           shuffle=False, path_imgrec=rec, path_imgidx=idx,
                           **_PIPE_KW)
    try:
        with pytest.raises(PipelineWorkerStorm) as err:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                p = it._procs[0]
                if p is not None and p.is_alive():
                    p.kill()
                    p.join(1.0)
                it.next()
        assert "max_respawns=1" in str(err.value)
        assert it.stats.snapshot()["respawns_epoch"] >= 2
    finally:
        it.close()


# ---------------------------------------------------------------------------
# heartbeat watchdog + elastic PS tier
# ---------------------------------------------------------------------------
def test_heartbeat_monitor_detects_silence_and_rejoin():
    dead = []
    mon = HeartbeatMonitor(timeout_s=0.2, on_dead=dead.append)
    mon.beat(0, step=5)
    mon.beat(1, step=9)
    assert mon.max_step() == 9
    t_end = time.monotonic() + 1.0
    while time.monotonic() < t_end and not mon.dead():
        mon.beat(0)                        # rank 0 keeps beating
        mon.check()
        time.sleep(0.05)
    assert mon.dead() == {1} and dead == [1]
    mon.beat(1)                            # rejoin clears death
    assert mon.dead() == set()


def test_ps_watchdog_reassigns_dead_worker_keys():
    """Kill a worker's heartbeat: the server watchdog must declare it
    dead, report it via num_dead, and move its keys to a live rank.
    (Deleting the watchdog makes this hang at num_dead==0 — the gate
    bites.)"""
    server = kvstore_ps.PSServer(port=0, num_workers=2,
                                 heartbeat_timeout_s=0.6,
                                 watchdog_poll_s=0.1)
    a = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
    b = kvstore_ps.PSClient("127.0.0.1", server.port, rank=1)
    try:
        a.start_heartbeat(0.1)
        b.start_heartbeat(0.1)
        a.init_array("wa", np.ones(4, np.float32))
        b.init_array("wb", np.full(4, 2.0, np.float32))
        assert server.key_owner("wa") == 0
        assert server.key_owner("wb") == 1
        assert a.request("key_owner", "wb")[1] == 1

        # silence rank 1 (its process "died"); rank 0 keeps beating
        b._hb.stop()
        b._hb = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if a.request("num_dead")[1] >= 1:
                break
            time.sleep(0.1)
        assert a.request("num_dead")[1] >= 1
        assert server.key_owner("wb") == 0     # reassigned to the live rank
        assert server._reassignments == [("wb", 1, 0)]
        # the store itself survived: rank 0 can still pull the value
        np.testing.assert_array_equal(a.pull_array("wb"),
                                      np.full(4, 2.0, np.float32))

        # rejoin: a fresh client for rank 1 beats again -> alive, but
        # ownership stays where the reassignment put it (single writer)
        b2 = kvstore_ps.PSClient("127.0.0.1", server.port, rank=1)
        b2.request("heartbeat", 1, 0)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and a.request("num_dead")[1]:
            time.sleep(0.1)
        assert a.request("num_dead")[1] == 0
        assert server.key_owner("wb") == 0
        b2.close()
    finally:
        a.close()
        b.close()
        server.stop()


def test_ps_bounded_staleness_gate_bites():
    """A push lagging the fleet beyond max_staleness is refused with
    StaleWorkerError; within the bound it lands.  Without the bound the
    same lag is silently accepted (the unguarded baseline), proving the
    gate is what does the refusing."""
    server = kvstore_ps.PSServer(port=0, num_workers=2, max_staleness=2)
    a = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
    b = kvstore_ps.PSClient("127.0.0.1", server.port, rank=1)
    try:
        a.init_array("w", np.zeros(4, np.float32))
        a.push_array("w", np.ones(4, np.float32), step=10)
        with pytest.raises(kvstore_ps.StaleWorkerError) as err:
            b.push_array("w", np.full(4, 9.0, np.float32), step=3)
        assert err.value.max_step == 10
        # the refused push did NOT land
        np.testing.assert_array_equal(a.pull_array("w"),
                                      np.ones(4, np.float32))
        # catching up (within the bound) is accepted
        b.push_array("w", np.full(4, 5.0, np.float32), step=9)
        np.testing.assert_array_equal(a.pull_array("w"),
                                      np.full(4, 5.0, np.float32))
    finally:
        a.close()
        b.close()
        server.stop()

    # no bound -> the same stale push is accepted (baseline)
    server2 = kvstore_ps.PSServer(port=0, num_workers=2)
    c = kvstore_ps.PSClient("127.0.0.1", server2.port, rank=0)
    try:
        c.init_array("w", np.zeros(4, np.float32))
        c.push_array("w", np.ones(4, np.float32), step=10)
        c.push_array("w", np.full(4, 9.0, np.float32), step=3)
        np.testing.assert_array_equal(c.pull_array("w"),
                                      np.full(4, 9.0, np.float32))
    finally:
        c.close()
        server2.stop()


def test_ps_client_reconnects_with_backoff():
    """A broken socket mid-conversation is redialed (with the shared
    backoff policy) and the request retried — PS restarts are blips."""
    server = kvstore_ps.PSServer(port=0, num_workers=1)
    cli = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
    try:
        cli.init_array("k", np.arange(4, dtype=np.float32))
        cli._sock.close()                  # simulate a dropped connection
        np.testing.assert_array_equal(cli.pull_array("k"),
                                      np.arange(4, dtype=np.float32))
        assert cli.reconnects >= 1
    finally:
        cli.close()
        server.stop()


def test_ps_chunked_push_restarts_after_reconnect(monkeypatch):
    """A reconnect mid-chunked-push must NOT corrupt the gradient: chunk
    staging is per-connection, so naively retrying just the broken chunk
    applies a gradient whose lost prefix is all zeros.  The client
    restarts the whole transfer; the value that lands is complete."""
    monkeypatch.setattr(kvstore_ps, "BIGARRAY_BOUND", 4)
    server = kvstore_ps.PSServer(port=0, num_workers=1)
    cli = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
    try:
        value = np.arange(1, 11, dtype=np.float32)       # 3 chunks of <=4
        cli.init_array("k", np.zeros(10, np.float32))
        orig, calls = cli.request, {"push_chunk": 0}
        def flaky(*msg):
            if msg[0] == "push_chunk":
                calls["push_chunk"] += 1
                if calls["push_chunk"] == 2:
                    cli._sock.close()      # connection dies before chunk 2
            return orig(*msg)
        cli.request = flaky
        cli.push_array("k", value)
        assert cli.reconnects == 1
        assert calls["push_chunk"] > 3     # the transfer restarted
        # the landed value has NO zero-filled prefix
        np.testing.assert_array_equal(cli.pull_array("k"), value)
    finally:
        cli.close()
        server.stop()


def test_ps_server_refuses_orphaned_push_chunk_tail():
    """Backstop behind the client restart: a push_chunk with start > 0
    on a connection with no staged prefix (fresh post-reconnect
    connection) is refused, never zero-filled."""
    server = kvstore_ps.PSServer(port=0, num_workers=1)
    try:
        server._handle(("init", "k", np.zeros(8, np.float32)))
        ctx = {"staging": {}, "snapshots": {}, "claimed_inits": set(),
               "rank": 0}
        reply = server._handle(
            ("push_chunk", "k", (8,), 4, 8, np.ones(4, np.float32), True,
             None), ctx)
        assert reply[0] == "err" and "staged prefix" in reply[1]
        np.testing.assert_array_equal(server._store["k"],
                                      np.zeros(8, np.float32))
    finally:
        server.stop()


def test_ps_barrier_is_not_retried_across_reconnect():
    """barrier is not idempotent (a retry after a lost reply would be
    counted twice, releasing the barrier early) — a broken socket makes
    it raise instead of silently resending.  Retry-safe commands still
    heal the connection afterwards."""
    server = kvstore_ps.PSServer(port=0, num_workers=2)
    cli = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
    try:
        cli._sock.close()
        with pytest.raises((OSError, ConnectionError)):
            cli.request("barrier")
        assert cli.reconnects == 0         # no transparent resend
        assert server._barrier_count == 0  # and no double-count
        assert cli.request("num_dead")[0] == "ok"
        assert cli.reconnects == 1
    finally:
        cli.close()
        server.stop()


def test_watchdog_survives_on_dead_callback_error():
    """An exception in the on_dead callback must not kill the watchdog
    thread — detection keeps running for later deaths."""
    deaths = []
    def bad_cb(rank):
        deaths.append(rank)
        raise RuntimeError("callback boom")
    mon = HeartbeatMonitor(timeout_s=0.2, poll_s=0.05, on_dead=bad_cb)
    mon.start()
    try:
        mon.beat(0)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not deaths:
            time.sleep(0.05)
        assert deaths == [0]
        mon.beat(0)                        # rejoin, then go silent again
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(deaths) < 2:
            time.sleep(0.05)
        assert deaths == [0, 0]            # the watchdog is still alive
    finally:
        mon.stop()


def test_ps_2bit_push_carries_step_through_staleness_gate():
    """With gradient compression on, pushes still carry the worker step:
    a lagging compressed push trips the staleness gate, recovers (pull +
    fast-forward) and re-sends — instead of mixing in unchecked."""
    from mxnet_tpu import kvstore as kv_mod
    server = kvstore_ps.PSServer(port=0, num_workers=2, max_staleness=2)
    fleet = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
    lag = kvstore_ps.PSClient("127.0.0.1", server.port, rank=1)
    try:
        kv = kv_mod.KVStore("local")
        kv._ps_client = lag
        kv._push_step = 0
        kv.set_gradient_compression({"threshold": 0.5})
        kv.init("w", mx.nd.zeros((4,)))
        fleet.push_array("w", np.ones(4, np.float32), step=10)
        kv.push("w", mx.nd.array(np.full(4, 2.0, np.float32)))
        # the gate bit (step 1 vs fleet 10 > bound 2) and recovery
        # fast-forwarded the step clock to the fleet's
        assert kv._push_step == 10
        assert server.monitor.step_of(1) == 10
        # the re-sent quantized payload landed: +threshold everywhere
        np.testing.assert_array_equal(lag.pull_array("w"),
                                      np.full(4, 0.5, np.float32))
    finally:
        fleet.close()
        lag.close()
        server.stop()


def test_chaos_drops_and_delays_kvstore_rpc():
    """The chaos harness can drop (raise) and delay kvstore RPCs at the
    probe site — the 'dropped push' failure mode, reproducible."""
    server = kvstore_ps.PSServer(port=0, num_workers=1)
    cli = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
    try:
        cli.init_array("k", np.zeros(2, np.float32))
        chaos.install([Fault("kvstore.request", 2, "raise")])
        cli.push_array("k", np.ones(2, np.float32))      # hit 1: clean
        with pytest.raises(chaos.ChaosError):
            cli.push_array("k", np.full(2, 7.0, np.float32))  # hit 2 drops
        # the dropped push never reached the server
        np.testing.assert_array_equal(cli.pull_array("k"),
                                      np.ones(2, np.float32))
    finally:
        chaos.uninstall()
        cli.close()
        server.stop()


@pytest.mark.slow
def test_ps_elastic_worker_death_and_rejoin_multiprocess(tmp_path):
    """Dist-marker elasticity case: real worker processes push with
    heartbeats; one is SIGKILLed, the watchdog reassigns its key, and a
    respawned worker rejoins and keeps pushing under the staleness
    bound."""
    server = kvstore_ps.PSServer(port=0, num_workers=2,
                                 heartbeat_timeout_s=1.0,
                                 watchdog_poll_s=0.2, max_staleness=1000)
    worker_src = (
        "import sys, time, numpy as np\n"
        "from mxnet_tpu import kvstore_ps\n"
        "port, rank = int(sys.argv[1]), int(sys.argv[2])\n"
        "cli = kvstore_ps.PSClient('127.0.0.1', port, rank=rank)\n"
        "step = 0\n"
        "cli.start_heartbeat(0.2, step_fn=lambda: step)\n"
        "cli.init_array('w%d' % rank, np.zeros(4, np.float32))\n"
        "print('READY', flush=True)\n"
        "while True:\n"
        "    step += 1\n"
        "    cli.push_array('w%d' % rank, np.full(4, step, np.float32),"
        " step=step)\n"
        "    time.sleep(0.1)\n")
    env = _cpu_env()
    procs = [subprocess.Popen(
        [sys.executable, "-c", worker_src, str(server.port), str(r)],
        env=env, stdout=subprocess.PIPE, text=True) for r in (0, 1)]
    try:
        for p in procs:
            assert p.stdout.readline().strip() == "READY"
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                server.key_owner("w1") is None:
            time.sleep(0.1)
        assert server.key_owner("w1") == 1
        procs[1].kill()                    # SIGKILL worker 1
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and server.key_owner("w1") != 0:
            time.sleep(0.1)
        assert server.key_owner("w1") == 0  # reassigned to live rank 0
        assert server.monitor.dead() == {1}
        # rejoin: respawn rank 1; it must come back alive and push again
        procs[1] = subprocess.Popen(
            [sys.executable, "-c", worker_src, str(server.port), "1"],
            env=env, stdout=subprocess.PIPE, text=True)
        assert procs[1].stdout.readline().strip() == "READY"
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and server.monitor.dead():
            time.sleep(0.1)
        assert server.monitor.dead() == set()
    finally:
        for p in procs:
            p.kill()
        server.stop()


# ---------------------------------------------------------------------------
# serving: liveness vs readiness, drain deadline
# ---------------------------------------------------------------------------
def _runner(warmup=True):
    from mxnet_tpu.serving import ModelRunner
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(3))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return ModelRunner(net, buckets=(1, 4), example_shape=(6,),
                       warmup=warmup)


def _get(port, path):
    import http.client
    import json
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    return resp.status, body


def test_serving_liveness_vs_readiness():
    from mxnet_tpu.serving import Server
    runner = _runner(warmup=False)
    server = Server(runner, port=0)
    _, port = server.start()
    try:
        # warming: alive but NOT ready
        status, body = _get(port, "/healthz")
        assert status == 503
        assert body == {"status": "warming", "alive": True, "ready": False,
                        # the hello-path provenance surface (ISSUE 12):
                        # untracked runners report a null digest
                        "provenance": {"default": None}}
        assert _get(port, "/livez") == (200, {"alive": True})
        assert _get(port, "/readyz")[0] == 503

        runner.warmup()
        status, body = _get(port, "/healthz")
        assert status == 200 and body["status"] == "ok" and body["ready"]
        assert _get(port, "/readyz") == (200, {"ready": True,
                                               "status": "ok"})
    finally:
        assert server.drain()
    # draining/stopped: batcher reports draining; livez semantics held
    assert server.status == "draining" and not server.ready


def test_serving_drain_honors_hard_deadline():
    from mxnet_tpu.serving import Batcher, Draining, Server
    import threading
    runner = _runner()
    release = threading.Event()
    real = runner.forward_batch
    runner.forward_batch = lambda x: (release.wait(30), real(x))[1]
    server = Server(runner, port=0, batch_timeout_ms=0.0,
                    drain_timeout_s=0.5)
    server.start()
    try:
        stuck = server.batcher.submit(np.zeros(6))    # wedges the worker
        time.sleep(0.2)                               # let it enter forward
        queued = server.batcher.submit(np.zeros(6))   # sits in the queue
        t0 = time.monotonic()
        clean = server.drain()
        assert time.monotonic() - t0 < 5.0            # did NOT wait 30s
        assert clean is False and server.drain_forced
        with pytest.raises(Draining):
            queued.result(1.0)                        # failed, not leaked
    finally:
        release.set()
    stuck.result(10.0)                                # in-flight completes


# ---------------------------------------------------------------------------
# SRC005 lint
# ---------------------------------------------------------------------------
@pytest.mark.analysis
def test_src005_unbounded_blocking_call():
    from mxnet_tpu.analysis import lint_source
    bad = "while True:\n    msg = q.get()\n"
    found = lint_source(bad)
    assert [f.rule_id for f in found] == ["SRC005"]
    # timeout, positional args, for-loops and str.join stay clean
    ok = ("while True:\n"
          "    a = q.get(timeout=1.0)\n"
          "    b = sock.recv(4096)\n"
          "for t in threads:\n"
          "    t.join()\n"
          "s = ' '.join(parts)\n")
    assert lint_source(ok) == []
    # inline suppression works
    sup = "while True:\n    x = q.get()  # mxlint: disable=SRC005\n"
    assert lint_source(sup) == []


@pytest.mark.analysis
def test_src005_sweep_of_shipped_worker_loops_is_clean():
    from mxnet_tpu.analysis import lint_worker_loops
    assert lint_worker_loops() == []


# ---------------------------------------------------------------------------
# bench stage
# ---------------------------------------------------------------------------
def test_resilience_bench_stage_reports_recovery_and_overhead():
    env = _cpu_env()
    env["MXTPU_RES_BENCH_STEPS"] = "40"    # keep the tier-1 box fast
    env["MXTPU_RES_BENCH_SERVER_PUSHES"] = "48"
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.resilience.bench"],
        capture_output=True, text=True, timeout=300, env=env, cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    import json
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["resilience_bitwise_ok"] is True
    assert rec["resilience_recovery_time_s"] > 0
    assert "resilience_checkpoint_overhead_pct" in rec
    assert rec["resilience_ckpt_bytes"] > 0
    # PS-tier durability metrics (ISSUE 7) ride the same stage
    assert rec["server_recovery_time_s"] > 0
    assert rec["wal_replay_rate_keys_per_s"] > 0
    assert rec["server_recovery_bitwise_ok"] is True
    assert "server_snapshot_overhead_pct" in rec
