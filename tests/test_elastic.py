"""Elastic ZeRO-1 tier (ISSUE 13): the sharded optimizer runtime
(``DataParallelTrainer(zero=1)``), shard-parallel resize-on-resume
checkpoints, and the chaos-proven elastic training supervisor.

Headline: ``test_headline_sigkill_1_of_4_resumes_at_3_bitwise`` —
chaos SIGKILLs rank 2 of a 4-rank fleet mid-epoch; the supervisor
names the dead rank in a versioned audit record, shrinks to size 3,
re-shards the latest manifest and resumes; the final params are
bitwise-equal to an uninterrupted size-3 run from the same checkpoint
with zero lost steps.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.parallel import DataParallelTrainer, make_mesh
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience import checkpoint as ckpt
from mxnet_tpu.resilience import supervisor as sup

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_DRIVER = os.path.join(_ROOT, "tools", "train_elastic.py")


@pytest.fixture(autouse=True)
def _no_chaos_leak():
    yield
    chaos.uninstall()


def _cpu_env(devices=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if devices:
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=%d" % devices)
    else:
        env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_CHAOS", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _zero_trainer(k, zero=1, seed=3, hidden=(32,), classes=10):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    for h in hidden:
        net.add(gluon.nn.Dense(h, activation="relu"))
    net.add(gluon.nn.Dense(classes))
    net.initialize(mx.init.Xavier())
    mesh = make_mesh((k,), ("data",), jax.devices()[:k])
    return DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh, zero=zero)


def _batches(n, batch=24, seed=0):
    rng = np.random.RandomState(seed)
    return [(mx.nd.array(rng.rand(batch, 16).astype(np.float32)),
             mx.nd.array(rng.randint(0, 10, batch).astype(np.int64)))
            for _ in range(n)]


def _params_blob(tr):
    return b"".join(np.asarray(p.data()._data).tobytes()
                    for p in tr._params_by_name.values())


def _full_state(tr):
    total = tr._zero_plan.total
    return [np.asarray(v)[:total].copy() for v in tr._zero_leaves()]


# ---------------------------------------------------------------------------
# the zero=1 runtime
# ---------------------------------------------------------------------------
def test_zero1_matches_replicated_numerics():
    """Same seed, same batches: the sharded update converges to the
    replicated trainer's params and momentum (float tolerance — the
    flat reduce-scatter sums in a different order)."""
    data = _batches(4)
    t0 = _zero_trainer(4, zero=0)
    for x, y in data:
        l0 = t0.step(x, y)
    t0.flush()
    t1 = _zero_trainer(4, zero=1)
    for x, y in data:
        l1 = t1.step(x, y)
    t1.flush()
    assert abs(float(l0.asnumpy()) - float(l1.asnumpy())) < 1e-4
    for p0, p1 in zip(t0._params_by_name.values(),
                      t1._params_by_name.values()):
        np.testing.assert_allclose(np.asarray(p0.data()._data),
                                   np.asarray(p1.data()._data),
                                   rtol=3e-5, atol=3e-6)
    # momentum parity: the flat sharded state vs per-param states,
    # concatenated in parameter order; the padding tail stays zero
    flat = np.concatenate([np.asarray(v) for v in t1._zero_leaves()])
    per = np.concatenate([np.asarray(v).ravel() for v in
                          jax.tree_util.tree_leaves(t0._states_raw)])
    total = t1._zero_plan.total
    np.testing.assert_allclose(flat[:total], per, rtol=3e-5, atol=3e-6)
    assert np.all(flat[total:] == 0.0)


def test_zero1_state_physically_sharded():
    """Each device holds exactly 1/K of every optimizer-state leaf —
    the ZeRO-1 memory saving is physical, not modeled."""
    t1 = _zero_trainer(4, zero=1)
    x, y = _batches(1)[0]
    t1.step(x, y)
    t1.flush()
    plan = t1._zero_plan
    for leaf in t1._zero_leaves():
        shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shapes == {(plan.shard,)}
        assert len(leaf.addressable_shards) == 4


def test_zero1_rejects_bad_configs():
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    # non-elementwise optimizer refused (flat-bucket correctness)
    with pytest.raises(ValueError, match="elementwise"):
        DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "lbsgd", {}, zero=1)
    with pytest.raises(ValueError, match="zero"):
        DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {}, zero=2)


def test_zero1_report_budget_relations():
    """The runtime tape at the pinned geometry: DST-clean, HBM drop >=
    optimizer-state x (1 - 1/K) below the twin, rs+ag parity with the
    inferred psum — the exact checks the STATIC_BUDGETS gate runs."""
    from mxnet_tpu.analysis import budget_models as bm
    report, findings, shard = bm.build_model("zero1_mlp_train_step")
    assert not findings, [str(f) for f in findings]
    x = shard.extras
    assert x["runtime_hbm_drop_bytes"] >= x["zero1_floor_bytes"]
    assert abs(x["runtime_rs_ag_bytes"]
               - x["runtime_inferred_psum_bytes"]) <= 64
    assert x["runtime_zero1_hbm_drop_pct"] > 20.0


def test_zero1_runtime_all_gather_mutation_fails_gate_rc2(tmp_path):
    """Deleting the RUNTIME all-gather (the parallel/zero.py seam)
    fails the unmodified STATIC_BUDGETS gate with DST007 named."""
    script = tmp_path / "mutate.py"
    script.write_text(
        "import os, sys\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu.parallel import zero\n"
        "zero.ZERO1_RUNTIME_ALL_GATHER = False\n"
        "from mxnet_tpu.analysis.__main__ import main\n"
        "sys.exit(main(['--cost', '--budget', %r]))\n"
        % os.path.join(_ROOT, "STATIC_BUDGETS.json"))
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=_ROOT,
                          env=_cpu_env(), timeout=600)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "DST007" in proc.stdout
    assert "all_gather" in proc.stdout


# ---------------------------------------------------------------------------
# shard-parallel checkpoints: resize-on-resume
# ---------------------------------------------------------------------------
def test_resize_parity_matrix(tmp_path):
    """Save at axis_size 4; restore at every size in {1, 2, 4}; the
    reassembled FULL state (params + optimizer) is bitwise-identical,
    and a k -> 4 re-save round-trips bitwise too (the 1→2→4→1 chain)."""
    d = str(tmp_path / "save4")
    t4 = _zero_trainer(4)
    for x, y in _batches(3):
        t4.step(x, y)
    t4.flush()
    t4.save_checkpoint(d, epoch=0, nbatch=2)
    ref_state, ref_params = _full_state(t4), _params_blob(t4)
    for k in (1, 2, 4):
        tk = _zero_trainer(k, seed=99)   # wrong seed: restore must win
        cursor = tk.restore_checkpoint(d)
        assert cursor["step"] == 3
        assert _params_blob(tk) == ref_params
        for a, b in zip(ref_state, _full_state(tk)):
            assert a.tobytes() == b.tobytes()
        d2 = str(tmp_path / ("resave%d" % k))
        tk.save_checkpoint(d2, epoch=0, nbatch=2)
        back = _zero_trainer(4, seed=77)
        back.restore_checkpoint(d2)
        assert _params_blob(back) == ref_params
        for a, b in zip(ref_state, _full_state(back)):
            assert a.tobytes() == b.tobytes()


def test_post_resize_training_is_deterministic(tmp_path):
    """Two same-size trainers restored from the same manifest train on
    bitwise-identical params after further steps."""
    d = str(tmp_path)
    t4 = _zero_trainer(4)
    data = _batches(4)
    for x, y in data[:2]:
        t4.step(x, y)
    t4.save_checkpoint(d, epoch=0, nbatch=1)
    outs = []
    for seed in (50, 60):
        t2 = _zero_trainer(2, seed=seed)
        t2.restore_checkpoint(d)
        for x, y in data[2:]:
            t2.step(x, y)
        t2.flush()
        outs.append(_params_blob(t2))
    assert outs[0] == outs[1]


def test_shard_integrity_named_error_and_fallback(tmp_path):
    """A corrupt shard raises ShardIntegrityError naming the shard; the
    latest-manifest scan falls back to the previous complete one."""
    d = str(tmp_path)
    payload = {"tag": "common"}
    ckpt.save_sharded_checkpoint(d, payload, [{"r": 0}, {"r": 1}],
                                 step=1, keep=3)
    ckpt.save_sharded_checkpoint(d, payload, [{"r": 0}, {"r": 1}],
                                 step=2, keep=3)
    manifests = ckpt.list_manifests(d)
    assert [s for s, _ in manifests] == [1, 2]
    rec = ckpt.load_sharded_checkpoint(manifests[-1][1])
    assert rec["world"] == 2 and rec["shards"][1] == {"r": 1}
    # corrupt a step-2 shard
    victim = [f for f in os.listdir(d)
              if f.startswith("ckpt-000000000002.shard-00001")][0]
    with open(os.path.join(d, victim), "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad")
    with pytest.raises(ckpt.ShardIntegrityError, match=victim[:20]):
        ckpt.load_sharded_checkpoint(manifests[-1][1])
    path, rec = ckpt.latest_sharded_checkpoint(d)
    assert rec["step"] == 1
    # a manifest whose shard file is MISSING is rejected by name too
    os.remove(os.path.join(d, victim))
    with pytest.raises(ckpt.ShardIntegrityError, match="missing"):
        ckpt.load_sharded_checkpoint(manifests[-1][1])


def test_sharded_prune_keeps_referenced_shards(tmp_path):
    d = str(tmp_path)
    for step in (1, 2, 3, 4):
        ckpt.save_sharded_checkpoint(d, {"s": step}, [{}, {}],
                                     step=step, keep=2)
    steps = [s for s, _ in ckpt.list_manifests(d)]
    assert steps == [3, 4]
    shard_files = [f for f in os.listdir(d) if f.endswith(".mxshard")]
    assert len(shard_files) == 4     # 2 ranks x 2 retained steps
    for _, path in ckpt.list_manifests(d):
        ckpt.load_sharded_checkpoint(path)   # every retained one loads


def test_kill_during_shard_write_keeps_previous_manifest(tmp_path):
    """SIGKILL mid shard-write (chaos site ckpt.shard_write): the torn
    save leaves the previous complete checkpoint authoritative."""
    d = str(tmp_path)
    script = (
        "import sys\n"
        "from mxnet_tpu.resilience import checkpoint as ck, chaos\n"
        "d = sys.argv[1]\n"
        "ck.save_sharded_checkpoint(d, {'s': 1}, [{}, {}, {}], step=1)\n"
        "chaos.install_from_env()\n"
        "ck.save_sharded_checkpoint(d, {'s': 2}, [{}, {}, {}], step=2)\n"
    )
    # chaos armed after the step-1 save: hit 2 is mid-way through the
    # step-2 shard set — shard 0 installed, the rest (and the manifest)
    # never written
    env = dict(_cpu_env(), MXTPU_CHAOS="ckpt.shard_write:2:kill")
    out = subprocess.run([sys.executable, "-c", script, d], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == -9, (out.returncode, out.stderr[-500:])
    path, rec = ckpt.latest_sharded_checkpoint(d)
    assert rec["step"] == 1 and rec["payload"] == {"s": 1}
    assert [s for s, _ in ckpt.list_manifests(d)] == [1]


def test_monolithic_checkpoint_refused_by_zero_trainer(tmp_path):
    t0 = _zero_trainer(2, zero=0)
    x, y = _batches(1)[0]
    t0.step(x, y)
    t0.save_checkpoint(str(tmp_path), epoch=0, nbatch=0)
    t1 = _zero_trainer(2, zero=1, seed=9)
    with pytest.raises(FileNotFoundError, match="sharded"):
        t1.restore_checkpoint(str(tmp_path))


# ---------------------------------------------------------------------------
# the supervisor: pure decisions, audit records, chaos
# ---------------------------------------------------------------------------
def _obs(exit_code, ranks, hbs, manifest_step, joins=(), restarts=0):
    return {"exit_code": exit_code, "ranks": list(ranks),
            "heartbeats": {str(r): dict(rank=r, enter_step=e,
                                        done_step=dn, trained_step=t)
                           for r, (e, dn, t) in hbs.items()},
            "manifest_step": manifest_step,
            "join_requests": list(joins), "target_steps": None,
            "restarts_used": restarts}


def test_supervisor_decide_is_pure_and_names_victim():
    decide = sup.ElasticSupervisor.decide
    # rank 2 entered step 12, never completed; rank 3 never entered
    obs = _obs(-9, [0, 1, 2, 3],
               {0: (12, 12, 11), 1: (12, 12, 11),
                2: (12, 11, 11), 3: (11, 11, 11)}, 11)
    d = decide(obs)
    assert d["action"] == "shrink" and d["dead_rank"] == 2
    assert d["ranks"] == [0, 1, 3] and d["steps_lost"] == 0
    assert decide(obs) == d                 # byte-identical replay
    # steps lost measured against the manifest
    obs2 = _obs(-9, [0, 1], {0: (8, 8, 7), 1: (8, 7, 7)}, 4)
    assert decide(obs2)["steps_lost"] == 3
    # shrink below min_size refused
    assert decide(obs2, min_size=2)["action"] == "halt"
    # no attributable victim: bounded restart, then halt
    obs3 = _obs(1, [0, 1], {0: (5, 5, 5), 1: (5, 5, 5)}, 5)
    assert decide(obs3)["action"] == "restart"
    assert decide(_obs(1, [0, 1], {0: (5, 5, 5), 1: (5, 5, 5)}, 5,
                       restarts=2))["action"] == "halt"
    # a clean exit completes; a yield with a join grows
    assert decide(_obs(0, [0, 1], {}, 5))["action"] == "complete"
    g = decide(_obs(sup.YIELD_EXIT_CODE, [0, 1],
                    {0: (5, 5, 5), 1: (5, 5, 5)}, 5, joins=[2]))
    assert g["action"] == "grow" and g["ranks"] == [0, 1, 2]


def test_supervisor_audit_schema_and_refusal(tmp_path):
    d = str(tmp_path)
    os.makedirs(os.path.join(d, "audit"), exist_ok=True)
    supv = sup.ElasticSupervisor(d, lambda *a: None, [0, 1])
    supv._commit({"action": "start", "ranks": [0, 1], "dead_rank": None,
                  "steps_lost": 0, "reason": "t"}, {"exit_code": None})
    trail = sup.read_audit(supv.audit_dir)
    assert len(trail) == 1
    assert trail[0]["schema_version"] == sup.AUDIT_SCHEMA_VERSION
    assert trail[0]["decision"]["action"] == "start"
    assert trail[0]["evidence"] == {"exit_code": None}
    # a NEWER schema is refused, not guessed at
    import json
    with open(os.path.join(supv.audit_dir, "audit-000099.json"),
              "w") as f:
        json.dump({"schema_version": sup.AUDIT_SCHEMA_VERSION + 1,
                   "seq": 99}, f)
    with pytest.raises(ValueError, match="schema_version"):
        sup.read_audit(supv.audit_dir)


def test_supervisor_decision_chaos_site(tmp_path):
    """A fault at supervisor.decision models a supervisor dying before
    the commit: the decision raises and NO audit record is written."""
    d = str(tmp_path)
    chaos.install([chaos.Fault("supervisor.decision", 1, "raise")])
    supv = sup.ElasticSupervisor(d, lambda *a: None, [0, 1])
    with pytest.raises(chaos.ChaosError):
        supv._commit({"action": "start", "ranks": [0, 1],
                      "dead_rank": None, "steps_lost": 0,
                      "reason": "t"}, {})
    assert sup.read_audit(supv.audit_dir) == []
    assert chaos.triggered()[0][:2] == ("supervisor.decision", 1)


def test_supervisor_decision_counter_registered(tmp_path):
    from mxnet_tpu.telemetry.metrics import registry
    supv = sup.ElasticSupervisor(str(tmp_path), lambda *a: None, [0])
    supv._commit({"action": "start", "ranks": [0], "dead_rank": None,
                  "steps_lost": 0, "reason": "t"}, {})
    text = registry().prometheus_text()
    assert "mxtpu_supervisor_decisions_total" in text
    assert 'action="start"' in text


def test_heartbeat_and_join_records_roundtrip(tmp_path):
    d = str(tmp_path)
    sup.write_heartbeat(d, 3, enter_step=7, done_step=6, trained_step=6)
    sup.write_heartbeat(d, 0, enter_step=7, done_step=7, trained_step=7)
    hbs = sup.read_heartbeats(d)
    assert set(hbs) == {0, 3}
    assert hbs[3]["done_step"] == 6
    sup.write_join_request(d, 5)
    assert sup.read_join_requests(d) == [5]
    sup.clear_join_requests(d)
    assert sup.read_join_requests(d) == []


# ---------------------------------------------------------------------------
# end-to-end: the headline chaos run and the grow path
# ---------------------------------------------------------------------------
def _run_driver(args, env, timeout=280):
    return subprocess.run([sys.executable, _DRIVER] + args, env=env,
                          capture_output=True, text=True,
                          timeout=timeout, cwd=_ROOT)


def test_headline_sigkill_1_of_4_resumes_at_3_bitwise(tmp_path):
    """SIGKILL rank 2 of 4 at step 12 (chaos train.step ordinal 47):
    the supervisor audits the dead rank, shrinks to [0, 1, 3], resumes
    from the step-11 manifest with 0 lost steps, and the final params
    are bitwise-equal to an uninterrupted size-3 run from the same
    checkpoint."""
    env = _cpu_env()
    run_a = str(tmp_path / "run")
    out_a = str(tmp_path / "a.bin")
    # kill at rank position 2 of 4, step 12: (12-1)*4 + 2 + 1 = 47
    out = _run_driver(
        ["--supervise", "--workdir", run_a, "--ranks", "0,1,2,3",
         "--steps", "16", "--batch", "24", "--checkpoint-every", "1",
         "--chaos", "train.step:47:kill", "--out", out_a], env)
    assert out.returncode == 0, out.stderr[-2000:]
    trail = sup.read_audit(os.path.join(run_a, "audit"))
    actions = [r["decision"]["action"] for r in trail]
    assert actions == ["start", "shrink", "complete"]
    shrink = trail[1]["decision"]
    assert shrink["dead_rank"] == 2
    assert shrink["ranks"] == [0, 1, 3]
    assert shrink["steps_lost"] == 0
    assert trail[1]["evidence"]["manifest_step"] == 11

    # reference: size-4 to step 11 (bitwise the same checkpoint), then
    # an UNINTERRUPTED size-3 run from it
    ref = str(tmp_path / "ref")
    out_b = str(tmp_path / "b.bin")
    out = _run_driver(["--workdir", ref, "--ranks", "0,1,2,3",
                       "--steps", "11", "--batch", "24",
                       "--checkpoint-every", "1"], env)
    assert out.returncode == 0, out.stderr[-2000:]
    # the two size-4 prefixes committed identical step-11 manifests
    dig_a = [m for m in ckpt.list_manifests(run_a) if m[0] == 11]
    dig_b = [m for m in ckpt.list_manifests(ref) if m[0] == 11]
    if dig_a and dig_b:
        a = ckpt.load_sharded_checkpoint(dig_a[0][1])["provenance"]
        b = ckpt.load_sharded_checkpoint(dig_b[0][1])["provenance"]
        assert a["digest"] == b["digest"]
    out = _run_driver(["--workdir", ref, "--ranks", "0,1,3",
                       "--steps", "16", "--batch", "24",
                       "--checkpoint-every", "1", "--resume",
                       "--out", out_b], env)
    assert out.returncode == 0, out.stderr[-2000:]
    with open(out_a, "rb") as f:
        blob_a = f.read()
    with open(out_b, "rb") as f:
        blob_b = f.read()
    assert blob_a and blob_a == blob_b


def test_grow_on_join_announcement(tmp_path):
    """A rank announcing itself mid-run makes the supervisor yield the
    job (SIGTERM -> checkpoint -> rc 3) and relaunch one rank larger;
    the audit trail shows the grow naming the new rank set."""
    import threading
    env = _cpu_env()
    run_d = str(tmp_path / "run")
    os.makedirs(run_d, exist_ok=True)

    def announce_when_running():
        # in-process join write: the CLI spelling (--announce) is
        # covered by test_announce_cli; a subprocess here would race
        # the 12-step job on a 1-core host
        import time as _t
        for _ in range(600):
            if sup.read_heartbeats(run_d):
                break
            _t.sleep(0.1)
        sup.write_join_request(run_d, 2)

    th = threading.Thread(target=announce_when_running)
    th.start()
    out = _run_driver(
        ["--supervise", "--workdir", run_d, "--ranks", "0,1",
         "--steps", "12", "--batch", "24", "--checkpoint-every", "1"],
        env)
    th.join()
    assert out.returncode == 0, out.stderr[-2000:]
    trail = sup.read_audit(os.path.join(run_d, "audit"))
    actions = [r["decision"]["action"] for r in trail]
    assert "grow" in actions, actions
    grow = trail[actions.index("grow")]["decision"]
    assert grow["ranks"] == [0, 1, 2]
    assert actions[-1] == "complete"


def test_announce_cli(tmp_path):
    """`train_elastic.py --announce R` writes the join record a running
    supervisor grows on."""
    env = _cpu_env()
    out = subprocess.run([sys.executable, _DRIVER, "--workdir",
                          str(tmp_path), "--announce", "7"], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-1000:]
    assert sup.read_join_requests(str(tmp_path)) == [7]


def test_elastic_bench_keys():
    """The bench stage's subprocess module emits the three gated keys
    with sane values (docs/elastic.md bench table)."""
    env = _cpu_env(devices=4)
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.resilience.elastic_bench"],
        capture_output=True, text=True, timeout=280, env=env, cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    import json
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["zero1_modeled_hbm_drop_pct"] > 20.0
    assert rec["elastic_resize_bitwise_ok"] is True
    assert rec["reshard_restore_ms"] > 0
    assert rec["supervisor_failover_steps_lost"] == 0
    assert rec["supervisor_failover_dead_rank"] == 1


def test_bench_compare_gates_elastic_keys(tmp_path):
    """tools/bench_compare.py gates the three elastic keys: a steps-
    lost regression or a shrunk HBM drop exits 2 naming the metric."""
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    try:
        import bench_compare as bc
    finally:
        sys.path.pop(0)
    import json

    def rec(n, parsed):
        return {"n": n, "cmd": "bench", "rc": 0, "parsed": parsed}

    good = {"zero1_modeled_hbm_drop_pct": 25.9,
            "reshard_restore_ms": 100.0,
            "supervisor_failover_steps_lost": 0}
    bad = {"zero1_modeled_hbm_drop_pct": 12.0,
           "reshard_restore_ms": 500.0,
           "supervisor_failover_steps_lost": 3}
    p6 = tmp_path / "BENCH_r06.json"
    p7 = tmp_path / "BENCH_r07.json"
    p6.write_text(json.dumps(rec(6, good)))
    p7.write_text(json.dumps(rec(7, dict(good))))
    report = bc.compare([str(p6), str(p7)])
    assert not report["regressions"]
    p7.write_text(json.dumps(rec(7, bad)))
    report = bc.compare([str(p6), str(p7)])
    assert set(report["regressions"]) == {
        "zero1_modeled_hbm_drop_pct", "reshard_restore_ms",
        "supervisor_failover_steps_lost"}


# ---------------------------------------------------------------------------
# telemetry: the zero1 collective shows up and the doctor names it
# ---------------------------------------------------------------------------
def test_zero1_bills_collective_phase_and_doctor_names_it(tmp_path):
    import mxnet_tpu.telemetry as tele
    from mxnet_tpu.telemetry.attribution import (doctor_report,
                                                 reset_attribution)
    tele.enable(str(tmp_path), rank=0)
    try:
        reset_attribution()
        t1 = _zero_trainer(2, zero=1)
        for x, y in _batches(3):
            t1.step(x, y)
        t1.flush()
        snap = tele.attribution().snapshot()
        assert snap["phases_s"].get("collective_or_ps", 0.0) > 0.0
        assert snap["context"] == {"collective_or_ps": "zero1"}
        # a metrics dump whose dominant phase is the zero1 collective
        # gets the specialized hint from the doctor
        import json
        doc = {"schema_version": 1, "source": "test",
               "attribution": {
                   "steps": 100, "wall_s": 10.0,
                   "phases_s": {"collective_or_ps": 8.0,
                                "dispatch": 1.0},
                   "unattributed_s": 1.0, "step_p50_s": 0.1,
                   "anomalies": 0,
                   "context": {"collective_or_ps": "zero1"}}}
        with open(os.path.join(str(tmp_path),
                               "metrics-worker0-123.json"), "w") as f:
            json.dump(doc, f)
        rep = doctor_report(str(tmp_path))
        rec = rep["ranks"]["worker0"]
        assert rec["dominant_phase"] == "collective_or_ps"
        assert "zero1 collective" in rec["hint"]
    finally:
        tele.disable()
        reset_attribution()
