"""mxnet_tpu.mlops: train→canary→serve auto-promotion + the fleet
capacity simulator (tier-1, ISSUE 12).

Contract points:
(a) checkpoint provenance: digest + (epoch, step, train_run_id) embedded
    at save, content-stable, surfaced by runners / fleet `/stats` /
    `/healthz`;
(b) the canary traffic split is deterministic: seeded hash-split reruns
    produce byte-identical canary/incumbent request sets at 1%/5%/25%,
    including under a mid-ramp hot swap;
(c) per-variant attribution: canary shed/degrade/breaker trouble never
    bills the incumbent's counters;
(d) the promotion controller promotes a good candidate through the full
    pinned ramp and rolls back a bad one, with a versioned audit trail
    (newer schemas refused);
(e) the simulator is deterministic, reproduces the tier-shed/breaker/
    degraded policies, and predicts the real host serving path within
    the documented <= 15% tolerance (reqs/sec + per-tier p99);
(f) capacity answers (required_replicas / tools/capacity.py) are
    deterministic and monotone;
(g) THE headline: a seeded chaos run where an injected-regression
    candidate is auto-rolled-back from canary with zero gold-tier SLO
    violations, the audit record naming the failed metric and the
    candidate's digest, and the decision sequence byte-identical across
    two full (retrain included) reruns.
"""
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.mlops import (AUDIT_SCHEMA_VERSION, PromotionController,
                             read_audit_records,
                             runner_from_trainer_checkpoint)
from mxnet_tpu.mlops.simulator import (FleetSimulator, SimConfig,
                                       burst_trace, diurnal_trace,
                                       required_replicas, trace_for_dau)
from mxnet_tpu.parallel import DataParallelTrainer
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience import checkpoint as ckpt
from mxnet_tpu.serving import ModelFleet, ModelRunner, RequestShed
from mxnet_tpu.serving.fleet import CanarySplit

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

FEAT = 8
NCLS = 3


def _build_net(hidden=16):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(hidden, activation="relu"))
    net.add(gluon.nn.Dense(NCLS))
    return net


def _train_checkpoint(seed, steps, ckdir, run_id, scramble=False):
    """A tiny deterministic training run ending in one snapshot.  With
    ``scramble`` the params are deterministically trashed afterwards —
    the injected regression the headline rolls back."""
    mx.random.seed(seed)
    np.random.seed(seed)
    net = _build_net()
    net.initialize(mx.init.Xavier())
    trainer = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.05}, run_id=run_id)
    rng = np.random.RandomState(seed)
    for i in range(steps):
        trainer.step(mx.nd.array(rng.rand(8, FEAT).astype(np.float32)),
                     mx.nd.array(rng.randint(0, NCLS, 8).astype(np.int64)))
    trainer.flush()
    if scramble:
        srng = np.random.RandomState(1234)
        for _, p in trainer._params_by_name.items():
            raw = np.asarray(p.data()._data)
            p.data()._set_data(
                (srng.rand(*raw.shape) * 4 - 2).astype(raw.dtype))
    return trainer.save_checkpoint(ckdir, epoch=0, nbatch=steps)


def _factory(path, rec):
    return runner_from_trainer_checkpoint(
        rec, _build_net, example_shape=(FEAT,), buckets=(1, 4))


def _hybrid_runner(seed=0, hidden=16, buckets=(1, 4)):
    mx.random.seed(seed)
    net = _build_net(hidden)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    return ModelRunner(net, buckets=buckets, example_shape=(FEAT,))


# ------------------------------------------------------------ provenance
def test_checkpoint_provenance_digest_and_surfacing(tmp_path):
    """Snapshots embed a content digest + (epoch, step, train_run_id);
    identical content digests identically; the digest rides the runner
    into fleet /stats and the /healthz hello."""
    d = str(tmp_path / "ck")
    path = _train_checkpoint(5, 2, d, "prov-run")
    rec = ckpt.load_checkpoint(path)
    prov = ckpt.provenance(rec)
    assert prov["train_run_id"] == "prov-run"
    assert prov["epoch"] == 0 and prov["step"] == 2
    assert len(prov["digest"]) == 64
    # content-stable ACROSS RERUNS: the identical training repeated (new
    # gluon gensym names and all) digests identically; different
    # training content does not
    rerun = ckpt.load_checkpoint(
        _train_checkpoint(5, 2, str(tmp_path / "ck_rr"), "prov-run"))
    assert ckpt.provenance(rerun)["digest"] == prov["digest"]
    other = ckpt.load_checkpoint(
        _train_checkpoint(6, 2, str(tmp_path / "ck2"), "prov-run"))
    assert ckpt.provenance(other)["digest"] != prov["digest"]
    # the generic digest helper is itself content-stable
    assert ckpt.payload_digest({"a": 1}) == ckpt.payload_digest({"a": 1})
    assert ckpt.payload_digest({"a": 1}) != ckpt.payload_digest({"a": 2})

    runner, rprov = _factory(path, rec)
    assert rprov["digest"] == prov["digest"]
    assert runner.provenance["digest"] == prov["digest"]
    fleet = ModelFleet(batch_timeout_ms=0.5)
    fleet.register("m", runner)
    st = fleet.stats_dict()
    assert st["models"]["m"]["provenance"]["digest"] == prov["digest"]
    assert st["models"]["m"]["provenance"]["train_run_id"] == "prov-run"
    assert fleet.provenance_digests() == {"m": prov["digest"]}
    fleet.drain()


def test_provenance_additive_and_loadable_back():
    """A pre-provenance record (no key) reads as None — the format stays
    backward readable."""
    assert ckpt.provenance({"version": 1, "step": 0, "payload": {}}) is None
    assert ckpt.provenance("junk") is None


# --------------------------------------------------- traffic split (b)
def _split_sets(schedule, seed, n=400):
    split = CanarySplit("c", schedule=schedule, seed=seed)
    out = []
    for _ in schedule:
        out.append(frozenset(i for i in range(n)
                             if split.routes_to_canary(i)))
        split.advance()
    return out


def test_traffic_split_deterministic_and_monotone():
    """Seeded hash-split reruns produce byte-identical canary request
    sets at 1%/5%/25%; ramping only grows the set; a different seed
    moves it."""
    a = _split_sets((0.01, 0.05, 0.25), seed=7, n=2000)
    b = _split_sets((0.01, 0.05, 0.25), seed=7, n=2000)
    assert a == b
    assert a[0] <= a[1] <= a[2]
    assert 2 <= len(a[0]) <= 60 and 60 <= len(a[1]) <= 140
    assert 400 <= len(a[2]) <= 600
    assert _split_sets((0.01, 0.05, 0.25), seed=8, n=2000)[2] != a[2]


def test_traffic_split_identical_under_mid_ramp_hot_swap():
    """The live-fleet half of (b): two reruns of a seeded request
    stream against a real fleet — with a ramp advance AND a hot swap of
    the incumbent mid-stream — route byte-identical canary/incumbent
    request-id sets at every fraction."""
    def run_once():
        fleet = ModelFleet(batch_timeout_ms=0.5)
        fleet.register("m", _hybrid_runner(seed=40))
        fleet.register("mc", _hybrid_runner(seed=41))
        fleet.set_canary("m", "mc", schedule=(0.01, 0.05, 0.25), seed=3)
        X = np.random.RandomState(0).rand(32, FEAT).astype(np.float32)
        routed = {0.01: [], 0.05: [], 0.25: []}
        frac = 0.01
        before = {}
        for i in range(300):
            if i == 100:
                frac = fleet.advance_canary("m")
            if i == 150:
                fleet.swap("m", _hybrid_runner(seed=42))  # mid-ramp swap
            if i == 200:
                frac = fleet.advance_canary("m")
            before[i] = fleet.entry("mc").batcher.stats.requests_total
            fleet.infer(X[i % 32], model="m", request_id=i, timeout=30)
            if fleet.entry("mc").batcher.stats.requests_total > before[i]:
                routed[frac].append(i)
        state = fleet.canary_state("m")
        fleet.drain()
        return routed, state

    r1, s1 = run_once()
    r2, s2 = run_once()
    assert r1 == r2
    assert s1 == s2
    assert s1["routed_canary"] == sum(len(v) for v in r1.values())
    # every fraction stage actually routed something at 5%/25%
    assert r1[0.25] and r1[0.05]


# ------------------------------------------- per-variant attribution (c)
def test_canary_shed_and_degrade_never_bills_incumbent():
    """The regression test the fleet satellite demands: a canary that
    sheds (tiny queue, pinned service hint, deadline'd requests) falls
    back to the incumbent — degraded/shed/rejected land on the CANARY's
    stats and the incumbent's ledger stays clean."""
    fleet = ModelFleet(batch_timeout_ms=0.0)
    fleet.register("m", _hybrid_runner(seed=50),
                   service_time_hint_ms=1.0, max_batch=4)
    # canary with a pinned huge service time: any deadline'd request
    # routed to it is shed at admission, deterministically
    fleet.register("mc", _hybrid_runner(seed=51),
                   service_time_hint_ms=100000.0, max_batch=4)
    fleet.set_canary("m", "mc", schedule=(0.5,), seed=0)
    X = np.random.RandomState(1).rand(16, FEAT).astype(np.float32)
    served = 0
    for i in range(120):
        fleet.infer(X[i % 16], model="m", request_id=i,
                    deadline_ms=5000.0, timeout=30)
        served += 1
    st = fleet.stats_dict()
    inc, can = st["models"]["m"], st["models"]["mc"]
    assert served == 120
    split = st["models"]["m"]["canary"]
    assert split["routed_canary"] > 20          # the 50% slice
    # every canary-routed request was shed by the canary and absorbed by
    # the incumbent — billed to the canary, never the incumbent
    assert can["shed_total"] == split["routed_canary"]
    assert can["degraded_total"] == split["routed_canary"]
    assert inc["shed_total"] == 0
    assert inc["degraded_total"] == 0
    assert inc["requests_total"] == 120         # it served everything
    assert can["requests_total"] == 0
    fleet.drain()


def test_canary_metrics_carry_variant_labels():
    """Registry samples split per variant: canary entries label
    canary_of, the split exports fraction/stage/routed counters."""
    fleet = ModelFleet(batch_timeout_ms=0.5)
    fleet.register("m", _hybrid_runner(seed=60))
    fleet.register("mc", _hybrid_runner(seed=61))
    fleet.set_canary("m", "mc", schedule=(0.25,), seed=0)
    samples = fleet._metrics_samples()
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    shed = {tuple(sorted(lab.items())): v
            for lab, v in by_name["mxtpu_serving_shed_total"]}
    assert (("canary_of", "m"), ("model", "mc")) in shed
    fr = by_name["mxtpu_serving_canary_fraction"]
    assert fr[0][0] == {"model": "m", "canary": "mc"}
    assert fr[0][1] == 0.25
    routed = {lab["variant"]: v
              for lab, v in by_name["mxtpu_serving_canary_routed_total"]}
    assert set(routed) == {"canary", "incumbent"}
    fleet.drain()


def test_canary_guards_and_deregister_protection():
    fleet = ModelFleet(batch_timeout_ms=0.5)
    fleet.register("m", _hybrid_runner(seed=70))
    fleet.register("mc", _hybrid_runner(seed=71))
    fleet.register("other", _hybrid_runner(seed=72, buckets=(1, 2)))
    with pytest.raises(MXNetError, match="canary itself"):
        fleet.set_canary("m", "m")
    with pytest.raises(MXNetError, match="schedule"):
        fleet.set_canary("m", "mc", schedule=(0.5, 0.1))
    fleet.set_canary("m", "mc", schedule=(0.1,), seed=0)
    # both halves of an armed split are deregister-protected
    with pytest.raises(MXNetError, match="canary"):
        fleet.deregister("mc")
    with pytest.raises(MXNetError, match="default"):
        fleet.deregister("m")
    fleet.clear_canary("m")
    assert fleet.canary_state("m") is None
    fleet.deregister("mc")
    assert "mc" not in fleet.models()
    fleet.drain()


# ------------------------------------------------ promotion controller
def _controller(fleet, watch, audit, golden, **kw):
    kw.setdefault("schedule", (0.01, 0.05, 0.25))
    kw.setdefault("min_stage_requests", 8)
    kw.setdefault("parity_threshold", 0.8)
    kw.setdefault("register_kwargs", {"service_time_hint_ms": 5.0})
    return PromotionController(fleet, "model", watch, _factory,
                               golden=golden, audit_dir=audit, **kw)


def _pump(fleet, X, rid, n=96, collect=None):
    for _ in range(n):
        i = rid[0]
        rid[0] += 1
        tier = ("gold", "silver", "bronze")[i % 3]
        t0 = time.perf_counter()
        try:
            fleet.infer(X[i % len(X)], model="model", tier=tier,
                        request_id=i, timeout=60)
        except RequestShed as e:
            if collect is not None:
                collect.append((tier, "shed", e.shed_at))
            continue
        if collect is not None:
            collect.append((tier, "served",
                            (time.perf_counter() - t0) * 1e3))


def test_promotion_good_candidate_promotes_through_ramp(tmp_path):
    """A good candidate (identical training, more steps) rides the full
    pinned 1%→5%→25% ramp and is promoted by hot swap; the audit trail
    is start→advance→advance→promote and the registry counted it."""
    ck_inc = str(tmp_path / "inc")
    watch = str(tmp_path / "watch")
    audit = str(tmp_path / "audit")
    path = _train_checkpoint(0, 2, ck_inc, "tp-inc")
    inc_runner, _ = _factory(path, ckpt.load_checkpoint(path))
    fleet = ModelFleet(batch_timeout_ms=0.5)
    fleet.register("model", inc_runner, tier_slos={"gold": 10000.0},
                   service_time_hint_ms=5.0)
    rng = np.random.RandomState(9)
    golden = rng.rand(16, FEAT).astype(np.float32)
    ctrl = _controller(fleet, watch, audit, golden, parity_threshold=0.5)
    _train_checkpoint(0, 3, watch, "tp-cand")
    cand_digest = ckpt.provenance(
        ckpt.latest_checkpoint(watch)[1])["digest"]
    X = rng.rand(64, FEAT).astype(np.float32)
    rid = [0]
    rec = ctrl.run(pump=lambda t: _pump(fleet, X, rid))
    assert rec is not None and rec["decision"]["decision"] == "promote"
    decisions = [d["decision"] for d in ctrl.decisions()]
    assert decisions == ["start_canary", "advance", "advance", "promote"]
    fracs = [d["fraction"] for d in ctrl.decisions()]
    assert fracs == [0.01, 0.05, 0.25, 0.25]
    # promoted: the incumbent now serves the candidate's exact bytes
    assert ctrl.incumbent_digest() == cand_digest
    assert fleet.models() == ["model"]          # canary cleaned up
    assert fleet.canary_state("model") is None
    # audit trail on disk matches, registry counted the decisions
    trail = read_audit_records(audit)
    assert [r["decision"]["decision"] for r in trail] == decisions
    assert all(r["schema_version"] == AUDIT_SCHEMA_VERSION
               for r in trail)
    n = ctrl.registry.counter(
        "mxtpu_promotion_decisions_total").value(
            model="model", decision="promote")
    assert n >= 1
    # the same digest is never re-canaried
    assert ctrl.poll() is None
    fleet.drain()


def test_audit_records_newer_schema_refused(tmp_path):
    audit = str(tmp_path)
    with open(os.path.join(audit, "audit-000001.json"), "w") as f:
        json.dump({"schema_version": AUDIT_SCHEMA_VERSION + 1,
                   "decision": {}}, f)
    with pytest.raises(ValueError, match="schema_version"):
        read_audit_records(audit)


def test_chaos_site_mlops_decision_is_wired(tmp_path):
    """The new probe site fires per evaluate tick with (model, state)
    ctx — a schedule can kill the controller at any decision boundary."""
    fleet = ModelFleet(batch_timeout_ms=0.5)
    fleet.register("model", _hybrid_runner(seed=80),
                   service_time_hint_ms=5.0)
    ctrl = _controller(fleet, str(tmp_path / "w"), str(tmp_path / "a"),
                       golden=None)
    chaos.install([chaos.Fault("mlops.decision", 2, "raise")])
    try:
        assert ctrl.evaluate() is None          # tick 1: clean
        with pytest.raises(chaos.ChaosError):   # tick 2: injected
            ctrl.evaluate()
        assert chaos.triggered()
    finally:
        chaos.uninstall()
    fleet.drain()


# ----------------------------------------------------------- simulator
def test_simulator_deterministic_and_tier_ordered():
    cfg = SimConfig(service_ms=5.0, buckets=(1, 4, 8),
                    batch_timeout_ms=2.0, max_queue=64)
    tr = diurnal_trace(8.0, 150.0, seed=3)
    r1 = FleetSimulator(cfg, replicas=2).run(tr)
    r2 = FleetSimulator(cfg, replicas=2).run(tr)
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)
    assert r1["served"] + r1["shed_total"] + r1["rejected_total"] \
        == r1["arrivals"]
    # an overload burst sheds the deadline'd lowest tier, never gold
    b = burst_trace(240, deadlines_ms={"bronze": 30.0})
    rb = FleetSimulator(cfg, replicas=1).run(b)
    assert rb["tiers"]["bronze"]["shed"] > 0
    assert rb["tiers"].get("gold", {}).get("shed", 0) == 0
    # tier ordering: on a deadline-free contended burst the gold tail
    # beats silver beats bronze (the queue is (tier, deadline, seq))
    big = SimConfig(service_ms=5.0, buckets=(1, 4, 8),
                    batch_timeout_ms=2.0, max_queue=1024)
    rq = FleetSimulator(big, replicas=1).run(burst_trace(240))
    assert rq["shed_total"] == 0 and rq["rejected_total"] == 0
    assert rq["tiers"]["gold"]["p99_ms"] < rq["tiers"]["silver"]["p99_ms"] \
        < rq["tiers"]["bronze"]["p99_ms"]


def test_simulator_breaker_and_degraded_policies():
    """Injected batch failures trip the modeled breaker; with a modeled
    fallback the refused slice is absorbed in degraded mode."""
    fallback = SimConfig(service_ms=2.0, buckets=(1, 4, 8),
                         batch_timeout_ms=1.0, max_queue=256)
    cfg = SimConfig(service_ms=5.0, buckets=(1, 4, 8),
                    batch_timeout_ms=1.0, max_queue=256,
                    breaker_threshold=3, breaker_open_ms=1000.0,
                    fail_batches=range(0, 6), fallback=fallback)
    tr = burst_trace(200, spacing_ms=2.0)
    rep = FleetSimulator(cfg, replicas=1).run(tr)
    assert rep["breaker_trips"] >= 1
    assert rep["failed_total"] > 0
    assert rep["degraded_total"] > 0
    assert rep["fallback"]["served"] == rep["degraded_total"]
    # no fallback -> the same refused slice is dropped, not served
    cfg2 = SimConfig(service_ms=5.0, buckets=(1, 4, 8),
                     batch_timeout_ms=1.0, max_queue=256,
                     breaker_threshold=3, breaker_open_ms=1000.0,
                     fail_batches=range(0, 6))
    rep2 = FleetSimulator(cfg2, replicas=1).run(tr)
    assert rep2["breaker_refused"] > 0 and rep2["degraded_total"] == 0


def test_simulator_validation_within_documented_tolerance():
    """The acceptance gate: modeled reqs/sec and per-tier p99 within
    15% of the real host serving bench — the exact bench-fleet scenario
    (parked-burst pattern, interleaved calibrate/predict pairs).

    Asserted on the BEST of the 5 interleaved pairs (the min-of-N side
    of the repo's wall-clock discipline): under 2x CPU load the median
    pair's windows can all be poisoned by scheduler noise that is not
    simulator error, while at least one tightly-interleaved pair stays
    clean.  The bench gate keeps trending the median keys
    (tools/bench_compare.py ``simulator_accuracy_pct``)."""
    from mxnet_tpu.mlops.bench import simulator_validation
    out = simulator_validation()
    assert out["simulator_best_accuracy_pct"] >= 85.0, out
    assert all(err <= 15.0
               for err in out["simulator_best_errors_pct"].values()), out


def test_capacity_deterministic_and_monotone():
    svc = {1: 8.0, 4: 18.0, 8: 32.0}
    cfg = SimConfig(service_ms=lambda b: svc[b], buckets=(1, 4, 8),
                    batch_timeout_ms=2.0, max_queue=128)
    deadlines = {"gold": 250.0, "silver": 400.0, "bronze": 150.0}
    tr = trace_for_dau(1_000_000, window_s=8.0, seed=0,
                       deadlines_ms=deadlines)
    k1, rep1 = required_replicas(cfg, tr, slo_tier="gold",
                                 slo_p99_ms=250.0)
    k2, rep2 = required_replicas(cfg, tr, slo_tier="gold",
                                 slo_p99_ms=250.0)
    assert (k1, rep1) == (k2, rep2)
    assert k1 >= 1 and rep1["tiers"]["gold"]["p99_ms"] <= 250.0
    # more users can never need fewer replicas
    tr_big = trace_for_dau(4_000_000, window_s=8.0, seed=0,
                           deadlines_ms=deadlines)
    k_big, _ = required_replicas(cfg, tr_big, slo_tier="gold",
                                 slo_p99_ms=250.0)
    assert k_big >= k1


def test_capacity_cli(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "capacity_tool", os.path.join(_ROOT, "tools", "capacity.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    args = tool.parse_args(["--dau", "1000000", "--slo-ms", "250",
                            "--window-s", "8"])
    k1, trace1, rep1 = tool.answer(args)
    k2, trace2, rep2 = tool.answer(args)
    assert k1 == k2 and trace1 == trace2
    assert rep1["tiers"]["gold"]["p99_ms"] <= 250.0
    assert tool.parse_service_ms("1=8,4=18") == {1: 8.0, 4: 18.0}
    with pytest.raises(SystemExit):
        tool.parse_service_ms("nonsense")


# ------------------------------------------------------ serve CLI (tools)
def test_serve_cli_canary_flags(tmp_path):
    """--canary NAME=PREFIX[@EPOCH] + --canary-fraction arm a
    single-stage deterministic split on the fleet; legacy flags parse
    unchanged."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "serve_canary_tool", os.path.join(_ROOT, "tools", "serve.py"))
    serve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve)

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="cn_fc1")
    out = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=NCLS, name="cn_fc2"),
        name="softmax")
    mod = mx.mod.Module(out)
    mod.bind(data_shapes=[("data", (4, FEAT))],
             label_shapes=[("softmax_label", (4,))], for_training=False)
    mod.init_params(mx.init.Xavier())
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 1)

    args = serve.parse_args([
        "--model", "mlp=%s@1" % prefix,
        "--canary", "mlp=%s@1" % prefix,
        "--canary-fraction", "0.25", "--canary-seed", "7",
        "--data-shape", str(FEAT), "--buckets", "1,4"])
    fleet = serve.build_fleet(args)
    assert fleet.models() == ["mlp", "mlp__canary"]
    state = fleet.canary_state("mlp")
    assert state["fraction"] == 0.25 and state["seed"] == 7
    fleet.drain()
    # a canary for an unregistered model is refused at parse/build
    bad = serve.parse_args(["--model", "mlp=%s@1" % prefix,
                            "--canary", "ghost=%s@1" % prefix,
                            "--data-shape", str(FEAT),
                            "--buckets", "1,4"])
    with pytest.raises(SystemExit, match="unregistered"):
        serve.build_fleet(bad)


def test_promote_cli_inspect_renders_audit(tmp_path, capsys):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "promote_tool", os.path.join(_ROOT, "tools", "promote.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    rec = {"schema_version": AUDIT_SCHEMA_VERSION,
           "decision": {"seq": 1, "model": "m", "decision": "rollback",
                        "stage": 0, "fraction": 0.01,
                        "candidate_digest": "ab" * 32,
                        "incumbent_digest": "cd" * 32,
                        "failed_metric": "golden_parity"},
           "evidence": {"golden_parity": 0.1}}
    with open(str(tmp_path / "audit-000001.json"), "w") as f:
        json.dump(rec, f)
    text = tool.render_audit([rec])
    assert "rollback" in text and "golden_parity" in text \
        and "abababab" in text
    assert tool.main(["--inspect", str(tmp_path)]) == 0
    assert "rollback" in capsys.readouterr().out
    # no mode given: usage hint, exit 2
    assert tool.main([]) == 2


def test_mlops_bench_keys():
    from mxnet_tpu.mlops.bench import capacity_answer
    out = capacity_answer()
    assert out["capacity_replicas_for_1m_dau"] >= 1
    assert out["capacity_trace_arrivals"] > 1000
    assert out["simulator_events_per_sec"] > 0
    # deterministic: the pinned scenario always answers the same
    assert capacity_answer()["capacity_replicas_for_1m_dau"] \
        == out["capacity_replicas_for_1m_dau"]


# ------------------------------------------------------- the headline
def _headline_run(root):
    """One full seeded chaos run: train incumbent, serve it with a gold
    SLO, train + scramble a candidate (the injected regression), run
    the controller loop under live tiered traffic with a chaos stall on
    the serving path.  Returns every observable the acceptance criteria
    assert on."""
    chaos.install([chaos.Fault("serving.batch", 3, "delay", 0.05)])
    try:
        ck_inc = os.path.join(root, "inc")
        watch = os.path.join(root, "watch")
        audit = os.path.join(root, "audit")
        path = _train_checkpoint(0, 3, ck_inc, "hl-incumbent")
        inc_runner, inc_prov = _factory(path, ckpt.load_checkpoint(path))
        fleet = ModelFleet(batch_timeout_ms=0.5)
        fleet.register("model", inc_runner,
                       tier_slos={"gold": 2000.0},
                       service_time_hint_ms=5.0)
        rng = np.random.RandomState(11)
        golden = rng.rand(16, FEAT).astype(np.float32)
        ctrl = _controller(fleet, watch, audit, golden)
        _train_checkpoint(0, 5, watch, "hl-candidate", scramble=True)
        cand_digest = ckpt.provenance(
            ckpt.latest_checkpoint(watch)[1])["digest"]
        X = rng.rand(64, FEAT).astype(np.float32)
        rid = [0]
        outcomes = []
        rec = ctrl.run(
            pump=lambda t: _pump(fleet, X, rid, collect=outcomes))
        stats = fleet.stats_dict()
        slo = fleet.entry("model").tier_slos["gold"]
        gold_lat = [v for tier, kind, v in outcomes
                    if tier == "gold" and kind == "served"]
        gold_shed = [v for tier, kind, v in outcomes
                     if tier == "gold" and kind == "shed"]
        triggered = chaos.triggered()
        fleet.drain()
        return {
            "terminal": rec,
            "decisions_blob": ctrl.decisions_blob(),
            "audit": read_audit_records(audit),
            "incumbent_digest": inc_prov["digest"],
            "candidate_digest": cand_digest,
            "stats": stats,
            "slo": slo,
            "gold_lat": gold_lat,
            "gold_shed": gold_shed,
            "triggered": triggered,
            "models_after": sorted(stats["models"]),
        }
    finally:
        chaos.uninstall()


def test_headline_regression_rollback_chaos(tmp_path):
    """THE acceptance test: an injected-regression candidate is
    auto-rolled-back from canary with zero gold-tier SLO violations,
    the audit record names the failed metric and the candidate's
    checkpoint digest, and the promote/rollback decision sequence is
    byte-identical across two full (retrain included) reruns."""
    r1 = _headline_run(str(tmp_path / "run1"))
    r2 = _headline_run(str(tmp_path / "run2"))

    for r in (r1, r2):
        # auto-rollback happened
        term = r["terminal"]
        assert term is not None
        assert term["decision"]["decision"] == "rollback"
        # the audit record names the metric and the checkpoint digest
        # that failed
        assert term["decision"]["failed_metric"] == "golden_parity"
        assert term["decision"]["candidate_digest"] == r["candidate_digest"]
        assert term["evidence"]["golden_parity"] < 0.8
        # the incumbent still serves its original bytes, canary gone
        m = r["stats"]["models"]["model"]
        assert m["provenance"]["digest"] == r["incumbent_digest"]
        assert r["models_after"] == ["model"]
        # zero gold-tier SLO violations: every gold request served, none
        # shed, and every end-to-end latency inside the declared SLO
        assert r["gold_shed"] == []
        assert r["gold_lat"] and max(r["gold_lat"]) <= r["slo"]
        assert m["tiers"].get("gold", {}).get("shed", 0) == 0
        # the chaos stall really fired during the run
        assert any(site == "serving.batch"
                   for site, _, _, _ in r["triggered"])
        # audit trail: start_canary then rollback, schema pinned
        kinds = [a["decision"]["decision"] for a in r["audit"]]
        assert kinds == ["start_canary", "rollback"]
        assert all(a["schema_version"] == AUDIT_SCHEMA_VERSION
                   for a in r["audit"])

    # byte-identical decision sequences across the two full reruns —
    # training, canary start, judgement and rollback included
    assert r1["decisions_blob"] == r2["decisions_blob"]
    assert json.dumps([a["decision"] for a in r1["audit"]],
                      sort_keys=True) \
        == json.dumps([a["decision"] for a in r2["audit"]],
                      sort_keys=True)
    # the retrained checkpoints digest identically too (full determinism)
    assert r1["candidate_digest"] == r2["candidate_digest"]
    assert r1["incumbent_digest"] == r2["incumbent_digest"]
