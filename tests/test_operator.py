"""Operator correctness vs the NumPy oracle
(reference: tests/python/unittest/test_operator.py, 6973 LoC)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def _rand(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


def test_unary_family():
    x = np.random.uniform(0.1, 2.0, (3, 4)).astype(np.float32)
    a = nd.array(x)
    for name, ref in [
        ("sqrt", np.sqrt), ("exp", np.exp), ("log", np.log),
        ("square", np.square), ("abs", np.abs), ("sign", np.sign),
        ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh),
        ("floor", np.floor), ("ceil", np.ceil), ("log1p", np.log1p),
        ("expm1", np.expm1), ("reciprocal", np.reciprocal),
        ("rsqrt", lambda v: 1 / np.sqrt(v)), ("cbrt", np.cbrt),
    ]:
        assert_almost_equal(getattr(nd, name)(a), ref(x), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.relu(nd.array(x - 1)), np.maximum(x - 1, 0))
    assert_almost_equal(nd.sigmoid(a), 1 / (1 + np.exp(-x)), rtol=1e-5)


def test_broadcast_family():
    x = _rand(2, 3, 4)
    y = _rand(1, 3, 1)
    a, b = nd.array(x), nd.array(y)
    assert_almost_equal(nd.broadcast_add(a, b), x + y, rtol=1e-6)
    assert_almost_equal(nd.broadcast_mul(a, b), x * y, rtol=1e-6)
    assert_almost_equal(nd.broadcast_maximum(a, b), np.maximum(x, y))
    assert_almost_equal(nd.broadcast_greater(a, b), (x > y).astype(np.float32))
    assert_almost_equal(nd.broadcast_to(nd.array(y), shape=(2, 3, 4)),
                        np.broadcast_to(y, (2, 3, 4)))


def test_reductions():
    x = _rand(2, 3, 4)
    a = nd.array(x)
    assert_almost_equal(nd.sum(a, axis=(0, 2)), x.sum(axis=(0, 2)), rtol=1e-5)
    assert_almost_equal(nd.sum(a, axis=1, exclude=True), x.sum(axis=(0, 2)), rtol=1e-5)
    assert_almost_equal(nd.mean(a, axis=1, keepdims=True), x.mean(axis=1, keepdims=True), rtol=1e-5)
    assert_almost_equal(nd.norm(a), np.sqrt((x ** 2).sum()), rtol=1e-5)
    assert_almost_equal(nd.argmax(a, axis=2), x.argmax(axis=2).astype(np.float32))
    assert_almost_equal(nd.prod(a, axis=0), x.prod(axis=0), rtol=1e-5)


def test_dot():
    x, y = _rand(4, 5), _rand(5, 6)
    assert_almost_equal(nd.dot(nd.array(x), nd.array(y)), x @ y, rtol=1e-5)
    assert_almost_equal(
        nd.dot(nd.array(x), nd.array(y.T), transpose_b=True), x @ y, rtol=1e-5)
    bx, by = _rand(3, 4, 5), _rand(3, 5, 2)
    assert_almost_equal(nd.batch_dot(nd.array(bx), nd.array(by)), bx @ by, rtol=1e-5)


def test_fully_connected():
    x, w, b = _rand(2, 3, 4), _rand(8, 12), _rand(8)
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b), num_hidden=8)
    ref = x.reshape(2, 12) @ w.T + b
    assert_almost_equal(out, ref, rtol=1e-5)
    out2 = nd.FullyConnected(nd.array(x), nd.array(w.reshape(8, 12)), no_bias=True,
                             num_hidden=8, flatten=True)
    assert_almost_equal(out2, x.reshape(2, 12) @ w.T, rtol=1e-5)


def test_convolution_vs_oracle():
    import torch
    import torch.nn.functional as F
    x, w, b = _rand(2, 3, 8, 8), _rand(5, 3, 3, 3), _rand(5)
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b), kernel=(3, 3),
                         num_filter=5, stride=(2, 2), pad=(1, 1))
    ref = F.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                   stride=2, padding=1).numpy()
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)
    # grouped
    xg, wg = _rand(1, 4, 6, 6), _rand(6, 2, 3, 3)
    outg = nd.Convolution(nd.array(xg), nd.array(wg), kernel=(3, 3), num_filter=6,
                          num_group=2, no_bias=True)
    refg = F.conv2d(torch.tensor(xg), torch.tensor(wg), groups=2).numpy()
    assert_almost_equal(outg, refg, rtol=1e-4, atol=1e-5)
    # dilated 1d
    x1, w1 = _rand(2, 3, 10), _rand(4, 3, 3)
    out1 = nd.Convolution(nd.array(x1), nd.array(w1), kernel=(3,), num_filter=4,
                          dilate=(2,), no_bias=True)
    ref1 = F.conv1d(torch.tensor(x1), torch.tensor(w1), dilation=2).numpy()
    assert_almost_equal(out1, ref1, rtol=1e-4, atol=1e-5)


def test_deconvolution_vs_oracle():
    import torch
    import torch.nn.functional as F
    x, w = _rand(2, 4, 5, 5), _rand(4, 3, 3, 3)
    out = nd.Deconvolution(nd.array(x), nd.array(w), kernel=(3, 3), num_filter=3,
                           stride=(2, 2), pad=(1, 1), adj=(1, 1), no_bias=True)
    ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w), stride=2,
                             padding=1, output_padding=1).numpy()
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_pooling_vs_oracle():
    import torch
    import torch.nn.functional as F
    x = _rand(2, 3, 8, 8)
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
    ref = F.max_pool2d(torch.tensor(x), 2, 2).numpy()
    assert_almost_equal(out, ref)
    out = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                     pool_type="avg")
    ref = F.avg_pool2d(torch.tensor(x), 3, 2, padding=1).numpy()
    assert_almost_equal(out, ref, rtol=1e-5)
    out = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                     pool_type="avg", count_include_pad=False)
    ref = F.avg_pool2d(torch.tensor(x), 3, 2, padding=1,
                       count_include_pad=False).numpy()
    assert_almost_equal(out, ref, rtol=1e-5)
    out = nd.Pooling(nd.array(x), global_pool=True, pool_type="avg", kernel=(1, 1))
    assert_almost_equal(out, x.mean(axis=(2, 3), keepdims=True), rtol=1e-5)
    # ceil ('full') convention
    x2 = _rand(1, 1, 7, 7)
    out = nd.Pooling(nd.array(x2), kernel=(3, 3), stride=(2, 2), pool_type="max",
                     pooling_convention="full")
    ref = F.max_pool2d(torch.tensor(x2), 3, 2, ceil_mode=True).numpy()
    assert_almost_equal(out, ref)


def test_batchnorm_train_and_inference():
    x = _rand(4, 3, 5, 5)
    gamma, beta = np.ones(3, np.float32), np.zeros(3, np.float32)
    mmean, mvar = np.zeros(3, np.float32), np.ones(3, np.float32)
    g, b = nd.array(gamma), nd.array(beta)
    mm, mv = nd.array(mmean), nd.array(mvar)
    with mx.autograd.train_mode():
        out = nd.BatchNorm(nd.array(x), g, b, mm, mv, fix_gamma=False, eps=1e-5,
                           momentum=0.9)
    bm = x.mean(axis=(0, 2, 3))
    bv = x.var(axis=(0, 2, 3))
    ref = (x - bm[None, :, None, None]) / np.sqrt(bv[None, :, None, None] + 1e-5)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)
    # moving stats updated
    assert_almost_equal(mm, 0.1 * bm, rtol=1e-4, atol=1e-6)
    assert_almost_equal(mv, 0.9 * 1.0 + 0.1 * bv, rtol=1e-4)
    # inference uses moving stats
    out_inf = nd.BatchNorm(nd.array(x), g, b, mm, mv, fix_gamma=False, eps=1e-5)
    ref_inf = (x - mm.asnumpy()[None, :, None, None]) / np.sqrt(
        mv.asnumpy()[None, :, None, None] + 1e-5)
    assert_almost_equal(out_inf, ref_inf, rtol=1e-4, atol=1e-5)


def test_softmax_family():
    x = _rand(3, 5)
    a = nd.array(x)
    ex = np.exp(x - x.max(axis=-1, keepdims=True))
    sm = ex / ex.sum(axis=-1, keepdims=True)
    assert_almost_equal(nd.softmax(a), sm, rtol=1e-5)
    assert_almost_equal(nd.log_softmax(a), np.log(sm), rtol=1e-4)
    assert_almost_equal(nd.softmax(a, axis=0),
                        np.exp(x - x.max(0)) / np.exp(x - x.max(0)).sum(0), rtol=1e-5)


def test_take_embedding_onehot_pick():
    w = _rand(10, 4)
    idx = np.array([[1, 3], [2, 9]], dtype=np.float32)
    out = nd.Embedding(nd.array(idx), nd.array(w), input_dim=10, output_dim=4)
    assert_almost_equal(out, w[idx.astype(int)])
    t = nd.take(nd.array(w), nd.array([0.0, 5.0]))
    assert_almost_equal(t, w[[0, 5]])
    oh = nd.one_hot(nd.array([0.0, 2.0]), depth=4)
    assert_almost_equal(oh, np.eye(4, dtype=np.float32)[[0, 2]])
    x = _rand(3, 5)
    p = nd.pick(nd.array(x), nd.array([0.0, 2.0, 4.0]), axis=1)
    assert_almost_equal(p, x[np.arange(3), [0, 2, 4]])


def test_shape_ops():
    x = _rand(2, 3, 4)
    a = nd.array(x)
    assert_almost_equal(nd.transpose(a, axes=(2, 0, 1)), x.transpose(2, 0, 1))
    assert_almost_equal(nd.flip(a, axis=1), np.flip(x, 1))
    assert_almost_equal(nd.tile(a, reps=(2, 1, 1)), np.tile(x, (2, 1, 1)))
    assert_almost_equal(nd.repeat(a, repeats=2, axis=1), np.repeat(x, 2, 1))
    parts = nd.split(a, num_outputs=3, axis=1)
    assert len(parts) == 3
    assert_almost_equal(parts[1], x[:, 1:2])
    sq = nd.split(a, num_outputs=3, axis=1, squeeze_axis=True)
    assert sq[0].shape == (2, 4)
    s = nd.slice(a, begin=(0, 1), end=(2, 3))
    assert_almost_equal(s, x[0:2, 1:3])
    sa = nd.slice_axis(a, axis=2, begin=1, end=3)
    assert_almost_equal(sa, x[:, :, 1:3])
    assert_almost_equal(nd.where(nd.array((x > 0).astype(np.float32)), a, -a),
                        np.where(x > 0, x, -x))
    p = nd.Pad(a.reshape((2, 3, 4, 1)).transpose((0, 3, 1, 2)), mode="constant",
               pad_width=(0, 0, 0, 0, 1, 1, 2, 2), constant_value=5)
    assert p.shape == (2, 1, 5, 8)


def test_topk_sort():
    x = _rand(3, 6)
    a = nd.array(x)
    v = nd.topk(a, k=2, ret_typ="value")
    ref = -np.sort(-x, axis=-1)[:, :2]
    assert_almost_equal(v, ref)
    idx = nd.topk(a, k=2, ret_typ="indices")
    assert_almost_equal(idx, np.argsort(-x, axis=-1)[:, :2].astype(np.float32))
    assert_almost_equal(nd.sort(a), np.sort(x, -1))
    assert_almost_equal(nd.argsort(a), np.argsort(x, -1).astype(np.float32))


def test_activation_leakyrelu():
    x = _rand(3, 4) * 2
    a = nd.array(x)
    assert_almost_equal(nd.Activation(a, act_type="softrelu"),
                        np.log1p(np.exp(x)), rtol=1e-5)
    assert_almost_equal(nd.LeakyReLU(a, act_type="leaky", slope=0.1),
                        np.where(x > 0, x, 0.1 * x), rtol=1e-6)
    assert_almost_equal(nd.LeakyReLU(a, act_type="elu", slope=1.0),
                        np.where(x > 0, x, np.expm1(x)), rtol=1e-5)


def test_norm_ops():
    x = _rand(2, 4, 3, 3)
    g, b = np.ones(4, np.float32) * 1.5, np.ones(4, np.float32) * 0.5
    out = nd.LayerNorm(nd.array(x), nd.array(np.ones(3, np.float32)),
                       nd.array(np.zeros(3, np.float32)), axis=-1)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    assert_almost_equal(out, (x - mean) / np.sqrt(var + 1e-5), rtol=1e-4, atol=1e-5)
    out = nd.InstanceNorm(nd.array(x), nd.array(g), nd.array(b), eps=1e-3)
    m = x.mean(axis=(2, 3), keepdims=True)
    v = x.var(axis=(2, 3), keepdims=True)
    ref = (x - m) / np.sqrt(v + 1e-3) * g[None, :, None, None] + b[None, :, None, None]
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)
    out = nd.L2Normalization(nd.array(x), mode="instance")
    ref = x / np.sqrt((x.reshape(2, -1) ** 2).sum(-1) + 1e-10)[:, None, None, None]
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_dropout_modes():
    x = np.ones((100, 100), np.float32)
    a = nd.array(x)
    out = nd.Dropout(a, p=0.5)  # inference: identity
    assert_almost_equal(out, x)
    with mx.autograd.train_mode():
        out = nd.Dropout(a, p=0.5)
    arr = out.asnumpy()
    frac = (arr == 0).mean()
    assert 0.4 < frac < 0.6
    kept = arr[arr != 0]
    assert_almost_equal(kept, np.full_like(kept, 2.0))


def test_sequence_ops():
    x = np.arange(24, dtype=np.float32).reshape(4, 2, 3)  # (seq, batch, feat)
    sl = np.array([2, 3], dtype=np.float32)
    out = nd.SequenceMask(nd.array(x), nd.array(sl), use_sequence_length=True,
                          value=-1.0)
    ref = x.copy()
    ref[2:, 0] = -1
    ref[3:, 1] = -1
    assert_almost_equal(out, ref)
    last = nd.SequenceLast(nd.array(x), nd.array(sl), use_sequence_length=True)
    assert_almost_equal(last, np.stack([x[1, 0], x[2, 1]]))
    rev = nd.SequenceReverse(nd.array(x), nd.array(sl), use_sequence_length=True)
    ref = x.copy()
    ref[:2, 0] = x[:2, 0][::-1]
    ref[:3, 1] = x[:3, 1][::-1]
    assert_almost_equal(rev, ref)


def test_upsampling_spatial():
    x = _rand(1, 2, 3, 3)
    out = nd.UpSampling(nd.array(x), scale=2, sample_type="nearest")
    assert_almost_equal(out, x.repeat(2, 2).repeat(2, 3))
    # bilinear grid sample identity
    n, c, h, w = 1, 1, 4, 4
    xx = _rand(n, c, h, w)
    ys = np.linspace(-1, 1, h, dtype=np.float32)
    xs = np.linspace(-1, 1, w, dtype=np.float32)
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    grid = np.stack([gx, gy])[None]
    out = nd.BilinearSampler(nd.array(xx), nd.array(grid))
    assert_almost_equal(out, xx, rtol=1e-5, atol=1e-6)


def test_cast_clip_misc():
    x = _rand(3, 3) * 3
    assert_almost_equal(nd.clip(nd.array(x), a_min=-1, a_max=1), np.clip(x, -1, 1))
    c = nd.Cast(nd.array(x), dtype="float16")
    assert c.dtype == np.float16
    assert_almost_equal(nd.add_n(nd.array(x), nd.array(x), nd.array(x)), 3 * x, rtol=1e-6)


def test_grad_simple_ops():
    check_numeric_gradient(lambda a: (a * a + a).sum(), [np.random.rand(3, 4)])
    check_numeric_gradient(lambda a, b: nd.dot(a, b).sum(),
                           [np.random.rand(3, 4), np.random.rand(4, 2)])
    check_numeric_gradient(lambda a: nd.sigmoid(a).sum(), [np.random.rand(3, 3)])
    check_numeric_gradient(
        lambda a: nd.FullyConnected(a, w_const, num_hidden=3, no_bias=True).sum(),
        [np.random.rand(2, 5)])


w_const = None


def setup_module():
    global w_const
    w_const = nd.array(np.random.rand(3, 5).astype(np.float32))
