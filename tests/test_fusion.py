"""Fusion tier (mxnet_tpu/analysis/fusion.py + ops/fused_optimizer.py;
docs/fusion.md): chain segmentation goldens on hand-built jaxprs,
byte-deterministic ranking, fused-vs-unfused optimizer numerics on CPU
interpret mode, the ZeRO-1 composition (fused shard update bitwise-
stable and tolerance-equal to the PR-13 runtime), the FUSED_OPTIMIZER
mutation seam killed through the real STATIC_BUDGETS.json gate
(subprocess rc=2, FUS001 named), the COST005 declared-cost lint, the
`--fusion` CLI/schema-4 JSON section, the doctor's `fusable` context
hint, and the host fusion-bench keys gated by bench_compare.
"""
import glob
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.analysis import fusion as mxfuse
from mxnet_tpu.analysis.cost import build_tape
from mxnet_tpu.ndarray import NDArray
from mxnet_tpu.ops import fused_optimizer as fo
from mxnet_tpu.ops import optimizer_ops as oo
from mxnet_tpu.parallel.trainer import DataParallelTrainer

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FLOAT_TOL = 1e-5


def _cpu_env(devices=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if devices:
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=%d" % devices)
    else:
        env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_CHAOS", None)
    env.pop("MXTPU_FUSED_OPTIMIZER", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


# ---------------------------------------------------------------------------
# chain segmentation goldens on hand-built jaxprs
# ---------------------------------------------------------------------------
def _sgd_mom_chain(w, g, m, lr):
    nm = 0.9 * m - lr * 1e-4 * w - lr * g
    return w + nm, nm


def test_elementwise_chain_found_and_ranked():
    shapes = [(256, 128), (128,), (64, 32)]
    avals = tuple(jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes)

    def unfused(ws, gs, ms, lr):
        outs = [_sgd_mom_chain(w, g, m, lr)
                for w, g, m in zip(ws, gs, ms)]
        return tuple(o[0] for o in outs), tuple(o[1] for o in outs)

    rep = mxfuse.fusion_from_fn(unfused, avals, avals, avals,
                                jnp.float32(0.1))
    # one chain per parameter, ranked by bytes saved: largest first
    assert len(rep.chains) == 3
    assert [c.kind for c in rep.chains] == ["elementwise"] * 3
    sizes = sorted((int(np.prod(s)) for s in shapes), reverse=True)
    assert [c.bytes_saved for c in rep.chains] == \
        sorted((c.bytes_saved for c in rep.chains), reverse=True)
    # the biggest chain belongs to the biggest parameter
    assert rep.chains[0].unfused_bytes > rep.chains[-1].unfused_bytes
    assert rep.total_bytes_saved > 0 and rep.bytes_saved_pct > 40
    assert sizes[0] > sizes[-1]  # geometry sanity


def test_ranking_is_byte_deterministic():
    aval = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    closed = jax.make_jaxpr(
        lambda w, g, m: _sgd_mom_chain(w, g, m, jnp.float32(0.1)))(
        aval, aval, aval)
    a = json.dumps(mxfuse.fusion_from_jaxpr(closed).as_dict(),
                   sort_keys=True)
    b = json.dumps(mxfuse.fusion_from_jaxpr(closed).as_dict(),
                   sort_keys=True)
    assert a == b


def test_dot_breaks_chain():
    aval = jax.ShapeDtypeStruct((64, 64), jnp.float32)

    def f(w, g):
        h = jnp.tanh(w) * 2.0
        y = h @ g                       # breaker
        return y * 3.0 + 1.0

    rep = mxfuse.fusion_from_fn(f, aval, aval)
    assert len(rep.chains) == 2
    for c in rep.chains:
        assert "dot_general" not in c.prims


def test_collective_breaks_chain():
    aval = jax.ShapeDtypeStruct((64,), jnp.float32)

    def f(w):
        h = w * 2.0 + 1.0
        r = lax.psum(h, "data")         # breaker
        return r * 3.0 - 1.0

    rep = mxfuse.fusion_from_fn(f, aval, axis_env=[("data", 8)])
    for c in rep.chains:
        assert "psum" not in c.prims
    # the two elementwise pairs stay separate chains
    assert len(rep.chains) == 2


def test_relayout_movement_breaks_chain():
    aval = jax.ShapeDtypeStruct((32, 64), jnp.float32)

    def f(w):
        h = w * 2.0 + 1.0
        t = h.T.reshape(-1)             # transpose + reshape: breakers
        return t * 3.0 - 1.0

    rep = mxfuse.fusion_from_fn(f, aval)
    assert len(rep.chains) == 2
    for c in rep.chains:
        assert not ({"transpose", "reshape"} & set(c.prims))


def test_shared_buffer_counted_once():
    """A chain reading the same external buffer through several eqns
    bills it ONCE in the fused pass (the donated/in-place w of every
    optimizer update)."""
    n = 128 * 128
    aval = jax.ShapeDtypeStruct((n,), jnp.float32)

    def f(w):
        a = w * 2.0
        b = w + 1.0          # second read of w
        return a * b

    rep = mxfuse.fusion_from_fn(f, aval)
    assert len(rep.chains) == 1
    c = rep.chains[0]
    assert c.external_in_bytes == n * 4          # w once, not twice
    assert c.external_out_bytes == n * 4
    # unfused: 3 eqns x (reads + writes); fused: w in, result out
    assert c.fused_bytes == 2 * n * 4
    assert c.bytes_saved == c.unfused_bytes - 2 * n * 4


def test_normalization_chain_kind_with_reduction_epilogue():
    aval = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    s = jax.ShapeDtypeStruct((128,), jnp.float32)

    def ln(x, scale, bias):
        mu = x.mean(axis=-1, keepdims=True)
        var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + 1e-5) * scale + bias

    rep = mxfuse.fusion_from_fn(ln, aval, s, s)
    assert len(rep.chains) == 1
    c = rep.chains[0]
    assert c.kind == "normalization"
    assert any(p.startswith("reduce_") for p in c.prims)
    assert c.bytes_saved > 0


def test_scan_scale_uniform_within_chain():
    aval = jax.ShapeDtypeStruct((64,), jnp.float32)

    def f(x):
        def body(c, _):
            return c * 2.0 + 1.0, ()
        out, _ = lax.scan(body, x, jnp.arange(4))
        return out * 3.0 - 1.0

    rep = mxfuse.fusion_from_jaxpr(jax.make_jaxpr(f)(aval))
    # the scanned body chain (scale 4) never merges with the scale-1
    # epilogue chain
    scales = sorted(c.scale for c in rep.chains)
    assert scales == [1, 4]


# ---------------------------------------------------------------------------
# fused kernels: numerics vs the unfused ops, bitwise rerun stability
# ---------------------------------------------------------------------------
def _rand(p, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(p).astype("f")),
            jnp.asarray(rng.randn(p).astype("f")),
            jnp.asarray(rng.randn(p).astype("f")),
            jnp.asarray(np.abs(rng.randn(p)).astype("f")))


@pytest.mark.parametrize("wd,clip", [(0.0, None), (1e-4, 0.5)])
def test_fused_sgd_momentum_matches_unfused(wd, clip):
    w, g, m, _ = _rand(5000)
    lr = jnp.float32(0.05)
    nw, nm = fo.fused_sgd_momentum(w, g, m, lr, momentum=0.9, wd=wd,
                                   clip_gradient=clip, interpret=True)
    rw, rm = oo.sgd_mom_update(w, g, m, lr=lr, momentum=0.9, wd=wd,
                               clip_gradient=-1.0 if clip is None
                               else clip)
    assert float(jnp.max(jnp.abs(nw - rw))) <= FLOAT_TOL
    assert float(jnp.max(jnp.abs(nm - rm))) <= FLOAT_TOL
    nw2, nm2 = fo.fused_sgd_momentum(w, g, m, lr, momentum=0.9, wd=wd,
                                     clip_gradient=clip, interpret=True)
    assert (np.asarray(nw) == np.asarray(nw2)).all()
    assert (np.asarray(nm) == np.asarray(nm2)).all()


def test_fused_plain_sgd_matches_unfused():
    w, g, _, _ = _rand(4096)
    lr = jnp.float32(0.05)
    nw = fo.fused_sgd(w, g, lr, wd=1e-4, interpret=True)
    rw = oo.sgd_update(w, g, lr=lr, wd=1e-4)
    assert float(jnp.max(jnp.abs(nw - rw))) <= FLOAT_TOL


def test_fused_adam_matches_unfused():
    w, g, m, v = _rand(5000, seed=2)
    lr, t = jnp.float32(0.01), jnp.int32(3)
    b1, b2, eps = 0.9, 0.999, 1e-8
    lr_t = lr * ((1 - b2 ** t) ** 0.5) / (1 - b1 ** t)
    nw, nm, nv = fo.fused_adam(w, g, m, v, lr_t, beta1=b1, beta2=b2,
                               epsilon=eps, wd=1e-4, interpret=True)
    rw, rm, rv = oo.adam_update(w, g, m, v, lr=lr_t, beta1=b1, beta2=b2,
                                epsilon=eps, wd=1e-4)
    for a, b in ((nw, rw), (nm, rm), (nv, rv)):
        assert float(jnp.max(jnp.abs(a - b))) <= FLOAT_TOL


def test_fused_update_zero_padding_tail_stays_zero():
    """The resize-losslessness lemma survives the fused kernels: a zero
    (w, g, state) tail maps to a zero tail (the flat space pads to
    whole kernel tiles)."""
    p = 5000                      # pads to 5120 inside the kernel
    w = jnp.concatenate([jnp.ones((p - 100,)), jnp.zeros((100,))])
    g = jnp.concatenate([jnp.ones((p - 100,)), jnp.zeros((100,))])
    m = jnp.zeros((p,))
    nw, nm = fo.fused_sgd_momentum(w, g, m, jnp.float32(0.1),
                                   momentum=0.9, wd=1e-4,
                                   interpret=True)
    assert (np.asarray(nw)[-100:] == 0).all()
    assert (np.asarray(nm)[-100:] == 0).all()


def test_fused_layer_norm_matches_jnp_and_grads():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(6, 32).astype("f"))
    s = jnp.asarray(rng.randn(32).astype("f"))
    b = jnp.asarray(rng.randn(32).astype("f"))

    def ref(x, s, b):
        mu = x.mean(axis=-1, keepdims=True)
        var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + 1e-5) * s + b

    got = fo.fused_layer_norm(x, s, b)
    assert float(jnp.max(jnp.abs(got - ref(x, s, b)))) <= FLOAT_TOL
    gr = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(
        x, s, b)
    gf = jax.grad(lambda *a: (fo.fused_layer_norm(*a) ** 2).sum(),
                  argnums=(0, 1, 2))(x, s, b)
    for a, b2 in zip(gr, gf):
        assert float(jnp.max(jnp.abs(a - b2))) <= 1e-4


def test_transformer_layer_norm_routes_to_fused(monkeypatch):
    from mxnet_tpu.transformer import layers as L
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 64).astype("f"))
    s = jnp.asarray(rng.randn(64).astype("f"))
    b = jnp.asarray(rng.randn(64).astype("f"))
    base = L.layer_norm(x, s, b)          # default host path: unfused
    monkeypatch.setenv("MXTPU_FUSED_LAYERNORM", "1")
    fused = L.layer_norm(x, s, b)
    assert float(jnp.max(jnp.abs(base - fused))) <= FLOAT_TOL
    # the fused spelling really is the Pallas kernel
    closed = jax.make_jaxpr(lambda *a: L.layer_norm(*a))(x, s, b)
    assert "pallas_call" in str(closed)


# ---------------------------------------------------------------------------
# trainer integration: replicated fused-vs-unfused, ZeRO-1 composition
# ---------------------------------------------------------------------------
def _mlp_trainer(opt, params, zero=0, seed=3):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    return DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                               opt, params, zero=zero)


def _run_steps(trainer, n=4, seed=5):
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(n):
        x = NDArray(jnp.asarray(rng.rand(8, 16).astype("f")))
        y = NDArray(jnp.asarray(rng.randint(0, 10, 8)))
        losses.append(float(trainer.step(x, y).asnumpy()))
    trainer.flush()
    params = [np.asarray(trainer._params_by_name[n_].data()._data)
              for n_ in trainer._train_names]
    return losses, params


@pytest.mark.parametrize("opt,oparams", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
def test_replicated_trainer_fused_matches_unfused(monkeypatch, opt,
                                                  oparams):
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "0")
    l0, p0 = _run_steps(_mlp_trainer(opt, oparams))
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    l1, p1 = _run_steps(_mlp_trainer(opt, oparams))
    l2, p2 = _run_steps(_mlp_trainer(opt, oparams))
    assert max(np.max(np.abs(a - b)) for a, b in zip(p0, p1)) <= FLOAT_TOL
    assert max(abs(a - b) for a, b in zip(l0, l1)) <= FLOAT_TOL
    # fused path is bitwise-deterministic across runs
    assert all((a == b).all() for a, b in zip(p1, p2))
    assert l1 == l2


def test_fused_kernel_traced_in_replicated_step(monkeypatch):
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    tr = _mlp_trainer("sgd", {"learning_rate": 0.1, "momentum": 0.9})
    rep = tr.cost_report(data_shape=(8, 16), label_shape=(8,))
    assert "pallas_call" in rep.per_primitive
    assert rep.unpriced_kernels == []
    # all four params fused into ONE flat bucket
    assert len(tr._groups) == 1 and len(tr._groups[0]) == 4


def test_zero1_fused_composition_subprocess(tmp_path):
    """The ZeRO-1 composition (ISSUE 15): on a real 4-way data axis the
    rs → FUSED-update → ag spelling matches the PR-13 unfused
    build_runtime_fns params within float tolerance, and the fused run
    repeats bitwise at equal steps (state still physically sharded)."""
    script = tmp_path / "zero_fused.py"
    script.write_text(textwrap.dedent("""\
        import os, sys
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import numpy as np, jax, jax.numpy as jnp
        import mxnet_tpu as mx
        from mxnet_tpu import gluon
        from mxnet_tpu.ndarray import NDArray
        from mxnet_tpu.parallel.trainer import DataParallelTrainer

        assert len(jax.devices()) == 4

        def trainer(seed=3):
            mx.random.seed(seed); np.random.seed(seed)
            net = gluon.nn.HybridSequential()
            net.add(gluon.nn.Dense(32, activation="relu"))
            net.add(gluon.nn.Dense(10))
            net.initialize(mx.init.Xavier())
            return DataParallelTrainer(
                net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                {"learning_rate": 0.1, "momentum": 0.9}, zero=1)

        def run(n=5):
            t = trainer()
            rng = np.random.RandomState(7)
            for _ in range(n):
                x = NDArray(jnp.asarray(rng.rand(8, 16).astype("f")))
                y = NDArray(jnp.asarray(rng.randint(0, 10, 8)))
                t.step(x, y)
            t.flush()
            state = t._states_raw[0]
            leaves = jax.tree_util.tree_leaves(state)
            # the optimizer state is PHYSICALLY sharded 4 ways
            for leaf in leaves:
                assert len(leaf.sharding.device_set) == 4
            params = [np.asarray(t._params_by_name[n_].data()._data)
                      for n_ in t._train_names]
            return params

        os.environ["MXTPU_FUSED_OPTIMIZER"] = "0"
        p_unfused = run()
        os.environ["MXTPU_FUSED_OPTIMIZER"] = "1"
        p_fused = run()
        p_fused2 = run()
        err = max(np.max(np.abs(a - b))
                  for a, b in zip(p_unfused, p_fused))
        assert err <= 1e-5, "fused-vs-unfused zero1 err %g" % err
        assert all((a == b).all() for a, b in zip(p_fused, p_fused2)), \\
            "fused zero1 rerun not bitwise"
        print("ZERO1_FUSED_OK err=%g" % err)
        """))
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=REPO,
                          env=_cpu_env(devices=4), timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ZERO1_FUSED_OK" in proc.stdout


# ---------------------------------------------------------------------------
# the budget gate: FUS001 + the FUSED_OPTIMIZER mutation seam
# ---------------------------------------------------------------------------
def test_fused_budget_model_clean_and_pinned():
    from mxnet_tpu.analysis.budget_models import (
        build_model, fused_update_fusion_numbers)
    rep, findings, shard = build_model("fused_optimizer_update")
    assert findings == []
    n = fused_update_fusion_numbers()
    # declared-vs-tape parity at the pinned geometry: the kernel reads
    # 8 bytes the unfused chain never streams — the loss-scale
    # reciprocal + finite flag in the SMEM scalar block [lr, inv_scale,
    # ok] (docs/precision.md) — so sgd sits exactly 8 over
    assert (n["sgd"]["kernel_bytes"]
            - n["sgd"]["chain_fused_bytes"]) == 8
    assert abs(n["adam"]["kernel_bytes"]
               - n["adam"]["chain_fused_bytes"]) <= 256
    assert n["sgd"]["saved_pct"] > 60 and n["adam"]["saved_pct"] > 70
    assert rep.transfer_bytes == 0 and rep.collective_bytes == 0


def test_fused_seam_kills_budget_gate(tmp_path):
    """Acceptance: FUSED_OPTIMIZER=False fails the UNMODIFIED
    STATIC_BUDGETS.json gate rc=2 naming FUS001 — from a subprocess."""
    script = tmp_path / "mutate.py"
    script.write_text(
        "import os, sys\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu.ops import fused_optimizer\n"
        "fused_optimizer.FUSED_OPTIMIZER = False\n"
        "from mxnet_tpu.analysis.__main__ import main\n"
        "sys.exit(main(['--cost', '--budget', %r]))\n"
        % os.path.join(REPO, "STATIC_BUDGETS.json"))
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, cwd=REPO,
                          env=_cpu_env(), timeout=600)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "FUS001" in proc.stdout
    assert "fused_optimizer_update" in proc.stdout


# ---------------------------------------------------------------------------
# COST005: the declared-cost lint + unpriced kernels named on the tape
# ---------------------------------------------------------------------------
def test_shipped_kernels_all_declare_costs():
    from mxnet_tpu.analysis import lint_kernel_costs
    from mxnet_tpu.analysis.cost import KERNEL_COSTS
    assert lint_kernel_costs() == []
    kernels, dynamic = mxfuse.pallas_kernels_used()
    assert dynamic == []
    assert set(kernels) <= set(KERNEL_COSTS)
    # the flash kernels are in the sweep (their annotation re-priced
    # ring_attention_fwd honestly)
    assert {"_fa_kernel", "_fa_dq_kernel", "_fa_dkv_kernel",
            "_fused_sgd_mom_kernel", "_fused_adam_kernel"} <= \
        set(kernels)


def test_unannotated_kernel_named_by_lint(tmp_path):
    opsdir = tmp_path / "ops"
    opsdir.mkdir()
    (opsdir / "rogue.py").write_text(textwrap.dedent("""\
        import functools
        from jax.experimental import pallas as pl

        def _rogue_kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        def rogue(x):
            kernel = functools.partial(_rogue_kernel)
            return pl.pallas_call(kernel, out_shape=x)(x)
        """))
    findings = mxfuse.lint_kernel_costs(root=str(opsdir))
    assert [f.rule_id for f in findings] == ["COST005"]
    assert "_rogue_kernel" in findings[0].message


def test_unpriced_kernel_named_on_tape():
    from jax.experimental import pallas as pl

    def _anon_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def f(x):
        return pl.pallas_call(
            _anon_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True)(x)

    tape = build_tape(jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((8, 128), jnp.float32)))
    assert tape.unpriced_kernels == ["_anon_kernel"]
    from mxnet_tpu.analysis.cost import analyze_tape, unpriced_findings
    rep = analyze_tape(tape)
    assert rep.unpriced_kernels == ["_anon_kernel"]
    rules = [f.rule_id for f in unpriced_findings(rep)]
    assert "COST005" in rules


def test_flash_kernels_priced_by_declaration():
    from mxnet_tpu.ops.pallas_kernels import flash_attention
    q = jax.ShapeDtypeStruct((2, 128, 4, 32), jnp.float32)
    closed = jax.make_jaxpr(
        lambda q, k, v: flash_attention(q, k, v, causal=True))(q, q, q)
    tape = build_tape(closed)
    pall = [op for op in tape.ops if op.prim == "pallas_call"]
    assert len(pall) == 1
    assert pall[0].params["kernel"] == "_fa_kernel"
    # declared flops: qk + pv dots = 4 * BH*T*Tk*D
    assert pall[0].flops == 4 * 8 * 128 * 128 * 32
    assert tape.unpriced_kernels == []


# ---------------------------------------------------------------------------
# report hooks: Symbol / trainer / CLI / schema
# ---------------------------------------------------------------------------
def test_symbol_fusion_report():
    from mxnet_tpu import symbol as sym
    data = sym.var("data")
    h = sym.FullyConnected(data, num_hidden=8, name="fu_fc1")
    a = sym.Activation(h, act_type="relu", name="fu_relu")
    out = sym.FullyConnected(a, num_hidden=4, name="fu_fc2")
    net = sym.SoftmaxOutput(out, name="fu_softmax")
    rep = net.fusion_report(shapes={"data": (4, 16)})
    assert rep is not None and rep.n_eqns > 0
    assert rep.chains and rep.total_bytes_saved > 0


def test_trainer_fusion_report_zero1():
    tr = _mlp_trainer("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                      zero=1)
    rep = tr.fusion_report(data_shape=(8, 16), label_shape=(8,),
                           declared_axis_size=8)
    assert rep.chains
    # the shard-local update chain is found in the runtime spelling
    assert any(c.kind == "elementwise" for c in rep.chains)


def test_trainer_fusion_report_mesh_tier():
    from mxnet_tpu.analysis.budget_models import (TP_GEOMETRY,
                                                  _tp_plan_and_program)
    from mxnet_tpu.parallel.mesh import MeshPlan
    g = TP_GEOMETRY
    _, _, block = _tp_plan_and_program()
    tr = DataParallelTrainer(
        block, None, "sgd",
        {"learning_rate": g["lr"], "momentum": g["momentum"]},
        mesh_plan=MeshPlan(data=g["data"], model=g["model"],
                           sequence=g["sequence"]))
    rep = tr.fusion_report(data_shape=(g["batch"], g["seq_len"]))
    assert rep.chains and rep.total_bytes_saved > 0


def test_cli_fusion_json_schema4():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--cost",
         "--fusion", "--json", "--model", "fused_optimizer_update"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema_version"] == 6
    fus = payload["fusion"]["fused_optimizer_update"]
    assert fus["n_chains"] >= 1 and fus["total_bytes_saved"] > 0
    assert fus["chains"][0]["kind"] == "elementwise"
    # without --fusion the section is absent (pre-4 consumers unaffected)
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", "--cost", "--json",
         "--model", "mlp_infer"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert "fusion" not in json.loads(proc.stdout)


def test_parse_log_reads_fusion_rows():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    doc = {"version": 1, "schema_version": 4, "findings": [],
           "fusion": {"m": {"total_bytes_saved": 9, "bytes_saved_pct":
                            50.0, "top_chain_pct": 30.0, "n_chains": 2,
                            "chains": []}}}
    rows = dict(parse_log.parse_analysis_json(doc))
    assert rows["fusion.m.total_bytes_saved"] == 9
    assert rows["fusion.m.top_chain_pct"] == 30.0


# ---------------------------------------------------------------------------
# doctor follow-through: the fusable context hint
# ---------------------------------------------------------------------------
def test_fusion_report_sets_fusable_context(tmp_path):
    from mxnet_tpu import telemetry
    telemetry.enable(str(tmp_path), rank=0, role="worker")
    try:
        tr = _mlp_trainer("sgd", {"learning_rate": 0.1,
                                  "momentum": 0.9})
        rep = tr.fusion_report(data_shape=(64, 16), label_shape=(64,))
        assert rep.top_chain_pct > mxfuse.FUSION_HINT_MIN_PCT
        ctx = telemetry.attribution().snapshot()["context"]
        assert ctx.get("dispatch") == "fusable"
        assert ctx.get("collective_or_ps") == "fusable"
    finally:
        telemetry.disable()


def test_doctor_names_fusion_knob(tmp_path):
    """A rank whose metrics dump shows dispatch dominant with the
    fusable context tag gets the fusion knob named in its hint."""
    from mxnet_tpu.telemetry.attribution import doctor_report
    dump = {
        "schema_version": 1,
        "attribution": {
            "steps": 100, "wall_s": 10.0,
            "phases_s": {"dispatch": 7.0, "input_wait": 1.0},
            "unattributed_s": 2.0, "step_p50_s": 0.1, "anomalies": 0,
            "context": {"dispatch": "fusable"},
        },
    }
    with open(os.path.join(str(tmp_path), "metrics-worker0-1.json"),
              "w") as f:
        json.dump(dump, f)
    report = doctor_report(str(tmp_path))
    rec = report["ranks"]["worker0"]
    assert rec["dominant_phase"] == "dispatch"
    assert "fus" in rec["hint"]
    assert "docs/fusion.md" in rec["hint"]


# ---------------------------------------------------------------------------
# bench stage + bench_compare gates
# ---------------------------------------------------------------------------
def test_fusion_bench_keys():
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.fusion_bench"],
        capture_output=True, text=True, cwd=REPO, env=_cpu_env(),
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["fusion_numerics_ok"] == 1.0
    assert rec["fused_optimizer_speedup_host"] > 1.0
    assert rec["modeled_fusion_bytes_saved_pct"] > 60
