"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's approach of testing distributed semantics without a
cluster (SURVEY.md §4: launch.py --launcher local); here
xla_force_host_platform_device_count gives 8 virtual devices so sharding /
collective paths compile and execute single-process.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def fixed_seed():
    """Fixed seeds per test (reference: tests/python/unittest/common.py with_seed)."""
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield
