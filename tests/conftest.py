"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's approach of testing distributed semantics without a
cluster (SURVEY.md §4: launch.py --launcher local); here
xla_force_host_platform_device_count gives 8 virtual devices so sharding /
collective paths compile and execute single-process.
"""
import os

# MXTPU_TEST_TPU=1 lifts the CPU pin so @pytest.mark.tpu tests (e.g. the
# non-degenerate TPU-vs-CPU consistency pass) can reach a real chip:
#   MXTPU_TEST_TPU=1 python -m pytest tests/ -m tpu
_USE_TPU = os.environ.get("MXTPU_TEST_TPU") == "1"

if not _USE_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if not _USE_TPU:
    # A site plugin may have force-registered a hardware backend via
    # jax.config (which outranks the env var) — pin the platform list back
    # to CPU so the virtual 8-device mesh is what tests actually run on.
    jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu" and jax.device_count() == 8, (
        "tests require the virtual 8-device CPU mesh; a site plugin initialized "
        f"JAX first ({jax.default_backend()}, {jax.device_count()} devices)")

import numpy as np
import pytest

# -- smoke tier -------------------------------------------------------------
# One (or two) fast representatives per subsystem, curated centrally so the
# tier's coverage is reviewable in one place.  `pytest -m smoke` must stay
# under 3 minutes on the 1-core bench host (VERDICT r4 item 8: the round
# driver runs it beside the bench so a slow full suite can never starve the
# perf capture again).  Tests can also self-mark with @pytest.mark.smoke.
SMOKE = {
    "test_autograd.py::test_basic_backward",
    "test_contrib.py::test_gluon_ctc_loss_blank_last",
    "test_contrib_proposal.py::test_sparse_embedding_forward",
    "test_contrib_py.py::test_text_vocabulary",
    "test_contrib_text.py::test_custom_embedding_loads_and_indexes",
    "test_custom_op.py::test_custom_sigmoid_forward_backward",
    "test_det_libsvm_io.py::test_basic_csr_batches",
    "test_dist.py::test_dist_sync_kvstore_two_processes",
    "test_exc_handling.py::test_shape_mismatch_raises",
    "test_exc_handling.py::test_state_intact_after_failure",
    "test_flash_backward.py::test_flash_grads_match_reference",
    "test_gluon.py::test_dense_shapes_and_forward",
    "test_gluon_model_zoo.py::test_unknown_name",
    "test_group2ctx.py::test_groups_land_different_shardings",
    "test_infer_shape.py::test_mlp_chain",
    "test_io.py::test_recordio_roundtrip",
    "test_io.py::test_indexed_recordio",
    "test_layout_bf16.py::test_conv_nhwc_matches_nchw",
    "test_linalg_cf_quant.py::test_linalg_potrf_potri",
    "test_losses_metrics_sched.py::test_l2_loss_vs_torch",
    "test_mesh_coverage.py::test_module_dp_matches_single_device",
    "test_model_store.py::test_plain_local_params_resolve",
    "test_module.py::test_module_predict_shapes",
    "test_ndarray.py::test_creation",
    "test_ndarray.py::test_arithmetic",
    "test_op_deep_nn.py::test_convolution_vs_torch",
    "test_operator.py::test_unary_family",
    "test_optimizer_ops.py::test_adam_update",
    "test_pallas_conv.py::test_padded_cout_slice",
    "test_parallel.py::test_data_parallel_training_decreases_loss",
    "test_quantization_int8.py::test_quantize_model_rewrites_conv_and_pooling",
    "test_registry_parity.py::test_registry_covers_reference_ops",
    "test_ring_attention.py::test_ring_matches_full",
    "test_rnn.py::test_rnn_op_vs_torch",
    "test_sparse_operator.py::test_cast_storage_csr",
    "test_symbol.py::test_infer_shape",
    "test_train.py::test_mlp_convergence",
}


def pytest_collection_modifyitems(config, items):
    matched = set()
    files_collected = set()
    for item in items:
        files_collected.add(item.fspath.basename)
        rel = "%s::%s" % (item.fspath.basename, item.name.split("[")[0])
        if rel in SMOKE:
            matched.add(rel)
            item.add_marker(pytest.mark.smoke)
    # a rename/deletion must not silently shrink the tier: any SMOKE entry
    # whose file WAS collected but whose test no longer exists is an
    # error.  Skipped when the invocation selects single tests by node-id
    # (pytest file.py::test_x) — partial collection would false-positive.
    if any("::" in str(a) for a in config.args):
        return
    ghosts = {s for s in SMOKE - matched
              if s.split("::")[0] in files_collected}
    if ghosts:
        raise pytest.UsageError(
            "smoke-tier entries match no collected test (renamed or "
            "deleted?): %s" % ", ".join(sorted(ghosts)))


@pytest.fixture(autouse=True)
def fixed_seed():
    """Fixed seeds per test (reference: tests/python/unittest/common.py with_seed)."""
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield
