"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's approach of testing distributed semantics without a
cluster (SURVEY.md §4: launch.py --launcher local); here
xla_force_host_platform_device_count gives 8 virtual devices so sharding /
collective paths compile and execute single-process.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# A site plugin may have force-registered a hardware backend via
# jax.config (which outranks the env var) — pin the platform list back
# to CPU so the virtual 8-device mesh is what tests actually run on.
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu" and jax.device_count() == 8, (
    "tests require the virtual 8-device CPU mesh; a site plugin initialized "
    f"JAX first ({jax.default_backend()}, {jax.device_count()} devices)")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def fixed_seed():
    """Fixed seeds per test (reference: tests/python/unittest/common.py with_seed)."""
    np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield
