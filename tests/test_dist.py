"""Distributed kvstore semantics across real processes
(reference: tests/nightly/dist_sync_kvstore.py run via
`tools/launch.py -n N --launcher local` — SURVEY.md §4)."""
import os
import subprocess
import sys
import textwrap

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 2, kv.num_workers
    # dense exact-sum: every worker pushes rank+1; pull must see the total
    kv.init("dense", mx.nd.zeros((8, 3)))
    kv.push("dense", mx.nd.ones((8, 3)) * (kv.rank + 1))
    out = mx.nd.zeros((8, 3))
    kv.pull("dense", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)

    # second round on the same key accumulates through the stored value
    kv.push("dense", mx.nd.ones((8, 3)))
    kv.pull("dense", out=out)
    np.testing.assert_allclose(out.asnumpy(), 2.0)  # no updater: replace

    kv.barrier()
    print("WORKER %%d OK" %% kv.rank)
""" % _ROOT)


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
def test_dist_sync_kvstore_two_processes(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no forced 8-device mesh in workers
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(worker)],
        env=env, capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-3000:]
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-3000:]
