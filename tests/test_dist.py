"""Distributed kvstore semantics across real processes
(reference: tests/nightly/dist_sync_kvstore.py run via
`tools/launch.py -n N --launcher local` — SURVEY.md §4)."""
import os
import subprocess
import sys
import textwrap

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 2, kv.num_workers
    # dense exact-sum: every worker pushes rank+1; pull must see the total
    kv.init("dense", mx.nd.zeros((8, 3)))
    kv.push("dense", mx.nd.ones((8, 3)) * (kv.rank + 1))
    out = mx.nd.zeros((8, 3))
    kv.pull("dense", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)

    # second round on the same key accumulates through the stored value
    kv.push("dense", mx.nd.ones((8, 3)))
    kv.pull("dense", out=out)
    np.testing.assert_allclose(out.asnumpy(), 2.0)  # no updater: replace

    kv.barrier()
    print("WORKER %%d OK" %% kv.rank)
""" % _ROOT)


_ASYNC_WORKER = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_async")
    assert kv.num_workers == 2, kv.num_workers
    kv.init("w", mx.nd.zeros((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))

    # async semantics: each push applies IMMEDIATELY server-side with no
    # cross-worker rendezvous.  Worker 1 pushes nothing until it OBSERVES
    # worker 0's three updates in the store — if pushes had a sync
    # barrier, worker 0 would block forever waiting for worker 1 and the
    # launch would time out.
    def poll(pred):
        out = mx.nd.zeros((4,))
        for _ in range(1200):
            kv.pull("w", out=out)
            if pred(out.asnumpy()[0]):
                return out.asnumpy()[0]
            time.sleep(0.05)
        raise AssertionError("store never reached expected state")

    if kv.rank == 0:
        for _ in range(3):
            kv.push("w", mx.nd.ones((4,)))  # sgd lr=1: each subtracts 1
        v = poll(lambda x: x <= -3.0 + 1e-5)
    else:
        poll(lambda x: x <= -3.0 + 1e-5)    # wait for worker 0's updates
        for _ in range(2):
            kv.push("w", mx.nd.ones((4,)))
    # both workers converge on all 5 pushes applied exactly once
    final = poll(lambda x: x <= -5.0 + 1e-5)
    np.testing.assert_allclose(final, -5.0, atol=1e-5)
    kv.barrier()
    print("ASYNC WORKER %%d OK" %% kv.rank)
""" % _ROOT)


_COMPRESSED_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_sync")
    kv.init("g", mx.nd.zeros((8,)))
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    # worker r pushes +/- values beyond the threshold
    sign = 1.0 if kv.rank == 0 else -1.0
    grad = mx.nd.array(np.array([2.0, -2.0, 0.1, 2.0, 0.0, -2.0, 2.0, 0.1],
                                np.float32) * sign)
    kv.push("g", grad)
    out = mx.nd.zeros((8,))
    kv.pull("g", out=out)
    # each worker quantized to +/-0.5; sum across the two opposite-signed
    # workers cancels exactly where both exceeded the threshold
    np.testing.assert_allclose(out.asnumpy(), 0.0, atol=1e-6)
    print("COMP WORKER %%d OK" %% kv.rank)
""" % _ROOT)


_FAKE_SSH = '''#!/usr/bin/env python3
"""Faithful ssh stand-in (no sshd in this image): receives the exact argv
real ssh would — option pairs, host, remote command words joined with
spaces and handed to the remote login shell — and executes that command
locally via sh -c.  The launcher's quoting/env/cwd contract is exercised
unchanged; only the transport is simulated."""
import subprocess, sys
args = sys.argv[1:]
while args and args[0].startswith("-"):
    flag = args.pop(0)
    if flag in ("-o", "-p", "-i", "-l", "-F"):
        args.pop(0)
host = args.pop(0)
with open(__file__ + ".log", "a") as f:
    f.write(host + "\\n")
sys.exit(subprocess.call(["/bin/sh", "-c", " ".join(args)]))
'''


def _launch(tmp_path, script, tag, timeout=None, launcher="local"):
    # load-tolerant deadline (VERDICT r5 weak 4: convergence-parity
    # failed under full-suite load, passed isolated): generous default,
    # overridable for even slower CI hosts
    timeout = timeout or int(os.environ.get("MXTPU_DIST_TIMEOUT", "600"))
    worker = tmp_path / ("worker_%s.py" % tag)
    worker.write_text(script)
    env = dict(os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no forced 8-device mesh in workers
    if launcher == "ssh":
        bindir = tmp_path / "bin"
        bindir.mkdir(exist_ok=True)
        shim = bindir / "ssh"
        shim.write_text(_FAKE_SSH)
        shim.chmod(0o755)
        env["PATH"] = "%s%s%s" % (bindir, os.pathsep, env.get("PATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", launcher, sys.executable, str(worker)],
        env=env, capture_output=True, text=True, timeout=timeout)
    return proc, proc.stdout + proc.stderr


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
@pytest.mark.slow
def test_dist_sync_kvstore_two_processes(tmp_path):
    proc, out = _launch(tmp_path, _WORKER, "sync")
    assert proc.returncode == 0, out[-3000:]
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-3000:]


@pytest.mark.slow
def test_dist_sync_kvstore_two_processes_ssh(tmp_path):
    """The same 2-worker dist_sync convergence through `--launcher ssh`
    against localhost (VERDICT r4 item 7; reference: the dmlc ssh tracker,
    ci/docker/runtime_functions.sh:732).  The image ships no sshd, so a
    faithful `ssh` shim on PATH receives the launcher's real ssh argv and
    runs the remote command locally — quoting, env handshake and cwd all
    cross the simulated transport."""
    proc, out = _launch(tmp_path, _WORKER, "sync_ssh", launcher="ssh")
    assert proc.returncode == 0, out[-3000:]
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-3000:]
    log = tmp_path / "bin" / "ssh.log"
    assert log.exists(), "ssh shim never invoked — launcher bypassed ssh"
    assert log.read_text().splitlines().count("localhost") == 2


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
@pytest.mark.slow
def test_dist_async_kvstore_two_processes(tmp_path):
    """True async semantics (reference: kvstore_dist_server.h:285): pushes
    apply per-arrival on the rank-0 parameter server, no barrier."""
    proc, out = _launch(tmp_path, _ASYNC_WORKER, "async")
    assert proc.returncode == 0, out[-3000:]
    assert "ASYNC WORKER 0 OK" in out and "ASYNC WORKER 1 OK" in out, \
        out[-3000:]


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
@pytest.mark.slow
def test_dist_sync_compressed_wire(tmp_path):
    """2-bit compression rides the wire as packed payloads and still sums
    exactly (reference: gradient_compression.h)."""
    proc, out = _launch(tmp_path, _COMPRESSED_WORKER, "comp")
    assert proc.returncode == 0, out[-3000:]
    assert "COMP WORKER 0 OK" in out and "COMP WORKER 1 OK" in out, \
        out[-3000:]


def test_pack_2bit_roundtrip_and_width():
    """Packed payload is actually 4 values/byte (the wire narrowing)."""
    import numpy as np
    from mxnet_tpu.kvstore_ps import pack_2bit, unpack_2bit
    vals = np.array([0.5, -0.5, 0.0, 0.5, -0.5, 0.0, 0.5], np.float32)
    packed, shape = pack_2bit(vals, 0.5)
    assert packed.dtype == np.uint8 and packed.size == 2  # ceil(7/4)
    back = unpack_2bit(packed, shape, 0.5)
    np.testing.assert_allclose(back, vals)


# ---------------------------------------------------------------------------
# Round 3: liveness, chunked big arrays, kill-resume (VERDICT r2 #9, #8)
# ---------------------------------------------------------------------------
_KILL_WORKER = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_async")
    kv.init("w", mx.nd.zeros((4,)))
    if kv.rank == 1:
        # die without goodbye: socket closes, server must notice
        os._exit(0)
    # survivor observes the death (reference: kvstore.h:339)
    for _ in range(1200):
        if kv.get_num_dead_node() >= 1:
            print("SURVIVOR SAW DEATH")
            break
        time.sleep(0.05)
    else:
        raise AssertionError("dead node never observed")
""" % _ROOT)


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
@pytest.mark.slow
def test_kill_a_worker_liveness(tmp_path):
    """A worker killed mid-run is observed by the survivor through
    get_num_dead_node (reference: ps-lite heartbeats, kvstore.h:339)."""
    proc, out = _launch(tmp_path, _KILL_WORKER, "kill")
    assert "SURVIVOR SAW DEATH" in out, out[-3000:]


def test_bigarray_chunked_push_pull(monkeypatch):
    """Keys above MXNET_KVSTORE_BIGARRAY_BOUND ride the wire in chunks
    (reference: kvstore_dist.h:522 EncodeDefaultKey sharding)."""
    import numpy as np
    from mxnet_tpu import kvstore_ps

    monkeypatch.setattr(kvstore_ps, "BIGARRAY_BOUND", 1000)
    server = kvstore_ps.PSServer(port=0, num_workers=1)
    try:
        client = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
        big = np.arange(5003, dtype=np.float32).reshape(-1)
        client.request("init", "big", np.zeros_like(big))
        client.push_array("big", big)
        got = client.pull_array("big")
        np.testing.assert_allclose(got, big)
        # num_dead: this client is alive
        assert client.request("num_dead")[1] == 0
        client.close()
        # closing the socket marks the rank dead
        import time
        probe = kvstore_ps.PSClient("127.0.0.1", server.port)
        for _ in range(100):
            if probe.request("num_dead")[1] == 1:
                break
            time.sleep(0.02)
        assert probe.request("num_dead")[1] == 1
        probe.close()
    finally:
        server.stop()


def test_checkpoint_kill_resume_matches_uninterrupted(tmp_path):
    """Mid-training kill + resume from checkpoint matches the
    uninterrupted trajectory exactly (reference posture: SURVEY §5
    checkpoint/resume; Module.save_checkpoint + load_epoch)."""
    import numpy as np
    import mxnet_tpu as mx

    rng = np.random.RandomState(3)
    X = rng.randn(256, 10).astype(np.float32)
    y = (np.arange(256) % 4).astype(np.float32)

    def build():
        data = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        out = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(h, num_hidden=4, name="fc2"),
            name="softmax")
        return out

    def train(mod, epochs, it):
        for _ in range(epochs):
            it.reset()
            for b in it:
                mod.forward_backward(b)
                mod.update()

    def new_it():
        return mx.io.NDArrayIter(X, y, 32)

    # identical initial draws for both runs: init_params consumes the
    # global RNG, so each run reseeds first
    mx.random.seed(1234)
    # uninterrupted: 6 epochs
    mod_a = mx.mod.Module(build())
    it = new_it()
    mod_a.bind(it.provide_data, it.provide_label)
    mod_a.init_params(initializer=mx.init.Xavier(rnd_type="uniform",
                                                 magnitude=2.0))
    mod_a.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9})
    train(mod_a, 6, it)
    ref_arg, _ = mod_a.get_params()

    # interrupted: 3 epochs -> checkpoint (params + optimizer states) ->
    # fresh process-equivalent Module -> resume -> 3 more epochs
    mx.random.seed(1234)
    mod_b = mx.mod.Module(build())
    it = new_it()
    mod_b.bind(it.provide_data, it.provide_label)
    mod_b.init_params(initializer=mx.init.Xavier(rnd_type="uniform",
                                                 magnitude=2.0))
    mod_b.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9})
    train(mod_b, 3, it)
    prefix = str(tmp_path / "ckpt")
    mod_b.save_checkpoint(prefix, 3)
    mod_b.save_optimizer_states(prefix + ".states")

    sym, arg, aux = mx.model.load_checkpoint(prefix, 3)
    mod_c = mx.mod.Module(sym)
    it = new_it()
    mod_c.bind(it.provide_data, it.provide_label)
    mod_c.set_params(arg, aux)
    mod_c.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9})
    mod_c.load_optimizer_states(prefix + ".states")
    train(mod_c, 3, it)
    res_arg, _ = mod_c.get_params()

    for k in ref_arg:
        np.testing.assert_allclose(res_arg[k].asnumpy(),
                                   ref_arg[k].asnumpy(), rtol=1e-5,
                                   atol=1e-5)


def test_abandoned_chunked_init_released_on_disconnect(monkeypatch):
    """A client that dies mid-chunked-init must release its claim so
    another worker's init can proceed instead of every push/pull on the
    key blocking forever (ADVICE r3: _pending_init leak)."""
    import time

    import numpy as np
    from mxnet_tpu import kvstore_ps

    monkeypatch.setattr(kvstore_ps, "BIGARRAY_BOUND", 1000)
    server = kvstore_ps.PSServer(port=0, num_workers=2)
    try:
        c1 = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
        big = np.arange(5003, dtype=np.float32)
        # claim the key, send ONE chunk, then die
        reply = c1.request("init_meta", "w", big.shape)
        assert reply[1] and not reply[2]  # fresh, not installed
        c1.request("init_chunk", "w", big.shape, 0, 1000, big[:1000],
                   False)
        c1.close()
        time.sleep(0.2)  # let the serve thread's finally release the claim
        # the second worker goes through the REAL client path: init_array
        # must wait out / re-contend the abandoned claim and install
        c2 = kvstore_ps.PSClient("127.0.0.1", server.port, rank=1)
        assert c2.init_array("w", big) == ("ok",)
        np.testing.assert_allclose(c2.pull_array("w"), big)
        c2.close()
    finally:
        server.stop()


def test_small_pull_single_round_trip_no_snapshot(monkeypatch):
    """pull_meta carries the client's chunk bound: a small key comes back
    inline (one round trip) and leaves no server-side snapshot behind
    (ADVICE r3: unconditional snapshot retention)."""
    import numpy as np
    from mxnet_tpu import kvstore_ps

    monkeypatch.setattr(kvstore_ps, "BIGARRAY_BOUND", 1000)
    server = kvstore_ps.PSServer(port=0, num_workers=1)
    try:
        client = kvstore_ps.PSClient("127.0.0.1", server.port, rank=0)
        small = np.arange(10, dtype=np.float32)
        client.request("init", "s", small)
        reply = client.request("pull_meta", "s", 1000)
        assert reply[3] is not None  # inline payload
        np.testing.assert_allclose(reply[3], small)
        np.testing.assert_allclose(client.pull_array("s"), small)
        # a big key still chunks: meta stages a snapshot, payload is None
        big = np.arange(5003, dtype=np.float32)
        client.request("init", "b", np.zeros_like(big))
        client.push_array("b", big)
        reply = client.request("pull_meta", "b", 1000)
        assert reply[3] is None
        np.testing.assert_allclose(client.pull_array("b"), big)
        client.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Round 4: 2-process DataParallelTrainer training run (VERDICT r3 #7;
# reference: tests/nightly/dist_lenet.py — a real model trained dist_sync)
# ---------------------------------------------------------------------------
def _trainer_data():
    import numpy as np
    rng = np.random.RandomState(42)
    X = rng.randn(64, 16).astype(np.float32)
    w_true = rng.randn(16, 4).astype(np.float32)
    y = (X @ w_true).argmax(1).astype(np.int64)
    return X, y


def _trainer_net_and_trainer(kv=None):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DataParallelTrainer, make_mesh

    mx.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    # local_devices: under a 2-process cluster jax.devices() is global and
    # [0] would be rank 0's device on BOTH workers (cross-host device_put)
    mesh = make_mesh((1,), ("data",), jax.local_devices()[:1])
    tr = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh, kvstore=kv)
    return net, tr


_TRAINER_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    sys.path.insert(0, %r)
    outdir = %r
    import numpy as np
    import mxnet_tpu as mx
    from test_dist import _trainer_data, _trainer_net_and_trainer

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    X, y = _trainer_data()
    net, tr = _trainer_net_and_trainer(kv)

    B = 32
    losses = []
    for step in range(30):
        b0 = (step * B) %% len(X)
        lo = b0 + rank * (B // nw)
        hi = lo + B // nw
        losses.append(float(tr.step(mx.nd.array(X[lo:hi]),
                                    mx.nd.array(y[lo:hi])).asscalar()))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    np.savez(os.path.join(outdir, "trainer_params_%%d.npz" %% rank),
             **{n: p.data().asnumpy()
                for n, p in net.collect_params().items()})
    kv.barrier()
    print("TRAINER WORKER %%d OK" %% rank)
""")


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
@pytest.mark.slow
def test_dist_trainer_convergence_matches_single_process(tmp_path):
    """2 processes x half batch under dist_sync converge AND land on
    exactly the params a single process sees on the full batch: pulled
    grad-sum / num_workers == full-batch gradient, so the whole training
    trajectory matches to float tolerance (reference:
    tests/nightly/dist_lenet.py asserts the same single-vs-dist parity)."""
    import numpy as np

    script = _TRAINER_WORKER % (_ROOT, os.path.dirname(__file__),
                                str(tmp_path))
    proc, out = _launch(tmp_path, script, "trainer", timeout=900)
    assert proc.returncode == 0, out[-3000:]
    assert "TRAINER WORKER 0 OK" in out and "TRAINER WORKER 1 OK" in out, \
        out[-3000:]

    # single-process reference trajectory: full batch, no kvstore
    X, y = _trainer_data()
    import mxnet_tpu as mx
    net, tr = _trainer_net_and_trainer()
    B = 32
    for step in range(30):
        b0 = (step * B) % len(X)
        tr.step(mx.nd.array(X[b0:b0 + B]), mx.nd.array(y[b0:b0 + B]))
    ref = {n: p.data().asnumpy() for n, p in net.collect_params().items()}

    for rank in (0, 1):
        got = np.load(tmp_path / ("trainer_params_%d.npz" % rank))
        assert set(got.files) == set(ref)
        for n in ref:
            np.testing.assert_allclose(got[n], ref[n], rtol=2e-4, atol=2e-5,
                                       err_msg="rank %d param %s" % (rank, n))


# ---------------------------------------------------------------------------
# launch.py coordinator/PS-port plumbing (ADVICE r5 items 1-2) — pure
# host-side, stays in tier-1
# ---------------------------------------------------------------------------
def _launch_mod():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch_tool", os.path.join(_ROOT, "tools", "launch.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_coordinator_address_mixed_hostfile_not_loopback():
    """localhost-first + remote hosts: remote ranks must never be told to
    dial 127.0.0.1 (they would dial themselves and the cluster wedges).
    Either a routable address is advertised (UDP-connect trick) or the
    launch errors asking for --coordinator."""
    m = _launch_mod()
    try:
        addr = m.coordinator_address(["localhost", "remote-host-1"])
    except SystemExit as e:
        assert "--coordinator" in str(e)   # no routable IP on this host
        return
    host = addr.rsplit(":", 1)[0]
    assert not host.startswith("127."), addr
    assert host not in ("localhost", "::1"), addr


def test_coordinator_address_all_local_stays_loopback():
    m = _launch_mod()
    addr = m.coordinator_address(["localhost", "localhost"])
    assert addr.startswith("127.0.0.1:")


def test_coordinator_address_remote_first_uses_that_host():
    m = _launch_mod()
    addr = m.coordinator_address(["worker-a", "localhost"])
    host, port = addr.rsplit(":", 1)
    assert host == "worker-a"
    assert 20000 <= int(port) <= 59999


def test_ps_port_override_reaches_workers():
    """--ps-port mirrors --coordinator: the pinned port must reach every
    rank's MXTPU_PS_PORT (the PS binds on rank 0's host where a port
    probed on the launcher proves nothing)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "echo", "--ps-port", "23456",
         "echo", "hi"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 2
    for line in lines:
        assert "MXTPU_PS_PORT=23456" in line, line
