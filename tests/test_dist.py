"""Distributed kvstore semantics across real processes
(reference: tests/nightly/dist_sync_kvstore.py run via
`tools/launch.py -n N --launcher local` — SURVEY.md §4)."""
import os
import subprocess
import sys
import textwrap

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 2, kv.num_workers
    # dense exact-sum: every worker pushes rank+1; pull must see the total
    kv.init("dense", mx.nd.zeros((8, 3)))
    kv.push("dense", mx.nd.ones((8, 3)) * (kv.rank + 1))
    out = mx.nd.zeros((8, 3))
    kv.pull("dense", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)

    # second round on the same key accumulates through the stored value
    kv.push("dense", mx.nd.ones((8, 3)))
    kv.pull("dense", out=out)
    np.testing.assert_allclose(out.asnumpy(), 2.0)  # no updater: replace

    kv.barrier()
    print("WORKER %%d OK" %% kv.rank)
""" % _ROOT)


_ASYNC_WORKER = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_async")
    assert kv.num_workers == 2, kv.num_workers
    kv.init("w", mx.nd.zeros((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))

    # async semantics: each push applies IMMEDIATELY server-side with no
    # cross-worker rendezvous.  Worker 1 pushes nothing until it OBSERVES
    # worker 0's three updates in the store — if pushes had a sync
    # barrier, worker 0 would block forever waiting for worker 1 and the
    # launch would time out.
    def poll(pred):
        out = mx.nd.zeros((4,))
        for _ in range(600):
            kv.pull("w", out=out)
            if pred(out.asnumpy()[0]):
                return out.asnumpy()[0]
            time.sleep(0.05)
        raise AssertionError("store never reached expected state")

    if kv.rank == 0:
        for _ in range(3):
            kv.push("w", mx.nd.ones((4,)))  # sgd lr=1: each subtracts 1
        v = poll(lambda x: x <= -3.0 + 1e-5)
    else:
        poll(lambda x: x <= -3.0 + 1e-5)    # wait for worker 0's updates
        for _ in range(2):
            kv.push("w", mx.nd.ones((4,)))
    # both workers converge on all 5 pushes applied exactly once
    final = poll(lambda x: x <= -5.0 + 1e-5)
    np.testing.assert_allclose(final, -5.0, atol=1e-5)
    kv.barrier()
    print("ASYNC WORKER %%d OK" %% kv.rank)
""" % _ROOT)


_COMPRESSED_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kv.create("dist_sync")
    kv.init("g", mx.nd.zeros((8,)))
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    # worker r pushes +/- values beyond the threshold
    sign = 1.0 if kv.rank == 0 else -1.0
    grad = mx.nd.array(np.array([2.0, -2.0, 0.1, 2.0, 0.0, -2.0, 2.0, 0.1],
                                np.float32) * sign)
    kv.push("g", grad)
    out = mx.nd.zeros((8,))
    kv.pull("g", out=out)
    # each worker quantized to +/-0.5; sum across the two opposite-signed
    # workers cancels exactly where both exceeded the threshold
    np.testing.assert_allclose(out.asnumpy(), 0.0, atol=1e-6)
    print("COMP WORKER %%d OK" %% kv.rank)
""" % _ROOT)


def _launch(tmp_path, script, tag, timeout=240):
    worker = tmp_path / ("worker_%s.py" % tag)
    worker.write_text(script)
    env = dict(os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no forced 8-device mesh in workers
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(worker)],
        env=env, capture_output=True, text=True, timeout=timeout)
    return proc, proc.stdout + proc.stderr


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
def test_dist_sync_kvstore_two_processes(tmp_path):
    proc, out = _launch(tmp_path, _WORKER, "sync")
    assert proc.returncode == 0, out[-3000:]
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-3000:]


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
def test_dist_async_kvstore_two_processes(tmp_path):
    """True async semantics (reference: kvstore_dist_server.h:285): pushes
    apply per-arrival on the rank-0 parameter server, no barrier."""
    proc, out = _launch(tmp_path, _ASYNC_WORKER, "async")
    assert proc.returncode == 0, out[-3000:]
    assert "ASYNC WORKER 0 OK" in out and "ASYNC WORKER 1 OK" in out, \
        out[-3000:]


@pytest.mark.skipif(os.environ.get("MXTPU_SKIP_DIST") == "1",
                    reason="dist test disabled")
def test_dist_sync_compressed_wire(tmp_path):
    """2-bit compression rides the wire as packed payloads and still sums
    exactly (reference: gradient_compression.h)."""
    proc, out = _launch(tmp_path, _COMPRESSED_WORKER, "comp")
    assert proc.returncode == 0, out[-3000:]
    assert "COMP WORKER 0 OK" in out and "COMP WORKER 1 OK" in out, \
        out[-3000:]


def test_pack_2bit_roundtrip_and_width():
    """Packed payload is actually 4 values/byte (the wire narrowing)."""
    import numpy as np
    from mxnet_tpu.kvstore_ps import pack_2bit, unpack_2bit
    vals = np.array([0.5, -0.5, 0.0, 0.5, -0.5, 0.0, 0.5], np.float32)
    packed, shape = pack_2bit(vals, 0.5)
    assert packed.dtype == np.uint8 and packed.size == 2  # ceil(7/4)
    back = unpack_2bit(packed, shape, 0.5)
    np.testing.assert_allclose(back, vals)
