"""Symbol API tests (reference: tests/python/unittest/test_symbol.py,
test_infer_shape.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_list_arguments():
    net = _mlp()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape():
    net = _mlp()
    args, outs, auxs = net.infer_shape(data=(8, 30))
    assert args == [(8, 30), (16, 30), (16,), (4, 16), (4,), (8,)]
    assert outs == [(8, 4)]


def test_infer_shape_conv_bn():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             name="conv0")
    net = mx.sym.BatchNorm(net, name="bn0")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    args, outs, auxs = net.infer_shape(data=(2, 3, 8, 8))
    assert args[1] == (8, 3, 3, 3)          # conv weight
    assert outs == [(2, 8, 4, 4)]
    assert net.list_auxiliary_states() == ["bn0_moving_mean", "bn0_moving_var"]
    assert auxs == [(8,), (8,)]


def test_infer_type():
    net = _mlp()
    args, outs, auxs = net.infer_type(data="float32")
    assert outs[0] == np.float32


def test_json_roundtrip(tmp_path):
    net = _mlp()
    fname = str(tmp_path / "sym.json")
    net.save(fname)
    net2 = mx.sym.load(fname)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    a1, o1, _ = net.infer_shape(data=(4, 10))
    a2, o2, _ = net2.infer_shape(data=(4, 10))
    assert o1 == o2 and a1 == a2


def test_symbol_compose():
    data = mx.sym.Variable("data")
    net1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net2 = mx.sym.Variable("in2")
    net2 = mx.sym.FullyConnected(net2, num_hidden=4, name="fc2")
    composed = net2(in2=net1)
    assert "fc1_weight" in composed.list_arguments()
    _, outs, _ = composed.infer_shape(data=(2, 10))
    assert outs == [(2, 4)]


def test_symbol_arithmetic_eval():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = 2.0 * a + b ** 2
    ex = c.bind(args={"a": mx.nd.array([1.0, 2.0]),
                      "b": mx.nd.array([3.0, 4.0])})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, [11.0, 20.0], rtol=1e-6)


def test_group_and_internals():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    grp = mx.sym.Group([fc1, act])
    assert len(grp.list_outputs()) == 2
    internals = act.get_internals()
    assert "fc1_output" in internals.list_outputs()
    sub = internals["fc1_output"]
    _, outs, _ = sub.infer_shape(data=(2, 4))
    assert outs == [(2, 8)]


def test_executor_forward_backward():
    net = _mlp()
    ex = net.simple_bind(grad_req="write", data=(8, 30))
    rng = np.random.RandomState(0)
    for name in ("fc1_weight", "fc2_weight"):
        arr = ex.arg_dict[name]
        arr._set_data(mx.nd.array(rng.randn(*arr.shape) * 0.1)._data)
    out = ex.forward(is_train=True,
                     data=rng.randn(8, 30).astype(np.float32),
                     softmax_label=rng.randint(0, 4, (8,)).astype(np.float32))
    assert out[0].shape == (8, 4)
    np.testing.assert_allclose(out[0].asnumpy().sum(), 8.0, rtol=1e-5)
    ex.backward()
    g = ex.grad_dict["fc1_weight"].asnumpy()
    assert np.abs(g).sum() > 0


def test_executor_grad_matches_autograd():
    """Executor vjp == imperative autograd on the same computation."""
    rng = np.random.RandomState(3)
    w = rng.randn(5, 7).astype(np.float32)
    x = rng.randn(4, 7).astype(np.float32)

    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=5, no_bias=True, name="fc")
    loss = mx.sym.MakeLoss(mx.sym.sum(fc * fc))
    ex = loss.bind(args={"data": mx.nd.array(x), "fc_weight": mx.nd.array(w)},
                   grad_req={"data": "null", "fc_weight": "write"})
    ex.forward(is_train=True)
    ex.backward()
    g_sym = ex.grad_dict["fc_weight"].asnumpy()

    wn = mx.nd.array(w)
    wn.attach_grad()
    with mx.autograd.record():
        y = mx.nd.FullyConnected(mx.nd.array(x), wn, num_hidden=5,
                                 no_bias=True)
        l = mx.nd.sum(y * y)
    l.backward()
    np.testing.assert_allclose(g_sym, wn.grad.asnumpy(), rtol=1e-4, atol=1e-5)


def test_eval_shape_caching_bucketing():
    """Same symbol at several shapes — jit caches per shape (bucketing)."""
    net = _mlp()
    ex = net.simple_bind(data=(4, 12))
    for t in (4, 6):
        out = ex.forward(data=np.zeros((4, 12), np.float32),
                         softmax_label=np.zeros((4,), np.float32))
        assert out[0].shape == (4, 4)
