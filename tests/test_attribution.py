"""Performance doctor (tier-1, ISSUE 10): per-step time attribution,
fleet straggler detection and the doctor CLI.

Contract points:
(a) StepAttribution windows: phase sums reconcile with measured step
    wall time (documented tolerance: overshoot ~0, unattributed >= 0),
    dominant-phase selection, per-window perf.phases flight records;
(b) a real trainer fit run attributes dispatch/input_wait/checkpoint
    time, embeds the snapshot in the metrics JSON and survives into the
    doctor report;
(c) the EWMA baseline flags a step-time regression (perf.anomaly) and
    queue growth (perf.queue_growth) into the ring — deterministically,
    via an injected clock;
(d) StragglerDetector: per-rank step-time p50 vs fleet median over
    heartbeat-style observations, perf.straggler events with the
    reported dominant phase, cooldown re-emission;
(e) the doctor reads a SIGKILLed rank's story from perf.phases ring
    windows alone (no metrics dump);
(f) the headline: a seeded 2-worker run with chaos `delay` faults at
    pipeline.dispatch on rank 1 — the doctor names input_wait as rank
    1's dominant phase, the server-side straggler detector flags rank 1
    with that phase in its perf.straggler event, and the same run with
    no fault reports balanced ranks.
"""
import glob
import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, telemetry
from mxnet_tpu.io.pipeline import pipeline_available
from mxnet_tpu.parallel import DataParallelTrainer
from mxnet_tpu.resilience import chaos
from mxnet_tpu.telemetry import flight
from mxnet_tpu.telemetry.attribution import (HINTS, PHASES,
                                             StepAttribution,
                                             StragglerDetector)

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _isolation():
    yield
    telemetry.disable()
    telemetry.reset_attribution()
    chaos.uninstall()


def _cpu_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("MXTPU_CHAOS", None)
    env.pop("MXTPU_TELEMETRY_DIR", None)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update({k: str(v) for k, v in extra.items()})
    return env


# ---------------------------------------------------------------------------
# (a) windows, reconciliation, dominant phase, ring records
# ---------------------------------------------------------------------------
def test_phase_window_reconciliation_and_ring(tmp_path):
    telemetry.enable(str(tmp_path), rank=0, role="worker")
    clock = [100.0]
    attr = StepAttribution(ring_every=4, now=lambda: clock[0])
    for step in range(1, 13):
        attr.on_step(step)
        attr.add_phase("dispatch", 0.002)
        attr.add_phase("input_wait", 0.006)
        clock[0] += 0.010          # window wall: 10ms
    attr.flush_window()
    snap = attr.snapshot()
    assert snap["steps"] == 12
    # reconciliation: wall == sum(phases) + unattributed, overshoot == 0
    psum = sum(snap["phases_s"].values())
    assert snap["overshoot_s"] == 0.0
    assert abs(snap["wall_s"] - (psum + snap["unattributed_s"])) < 1e-9
    assert abs(snap["wall_s"] - 0.120) < 1e-9
    assert abs(snap["unattributed_s"] - 0.024) < 1e-9
    assert snap["dominant_phase"] == "input_wait"
    assert abs(snap["step_p50_s"] - 0.010) < 1e-9
    # unknown phases are rejected, not silently dropped
    with pytest.raises(ValueError):
        attr.add_phase("not_a_phase", 0.1)
    # perf.phases flight windows: 3 (every 4 steps) + no partial left
    ring = glob.glob(str(tmp_path / "*.mxring"))[0]
    _, events = flight.read_ring(ring)
    wins = [e for e in events if e["kind"] == "perf.phases"]
    assert len(wins) == 3
    assert wins[0]["steps"] == 4
    assert wins[0]["phase"] == "input_wait"
    assert wins[0]["phases"]["input_wait"] == pytest.approx(0.024)
    # every phase has a doctor hint and a PHASES entry (the TEL002
    # contract, asserted live too)
    assert set(HINTS) == set(PHASES)


def test_trainer_fit_attributes_phases_and_dumps(tmp_path):
    tele = tmp_path / "tele"
    os.makedirs(tele)
    telemetry.enable(str(tele), rank=0, role="worker")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    tr = DataParallelTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             "sgd", {"learning_rate": 0.05})
    rng = np.random.RandomState(0)
    it = mx.io.NDArrayIter(rng.rand(96, 12).astype(np.float32),
                           rng.randint(0, 10, 96).astype(np.int64), 16)
    tr.fit(it, num_epoch=2, checkpoint_dir=str(tmp_path / "ck"),
           checkpoint_every=5)
    snap = telemetry.attribution().snapshot()
    assert snap["steps"] == 12
    phases = snap["phases_s"]
    assert phases["dispatch"] > 0
    assert phases["checkpoint"] > 0
    assert phases["input_wait"] >= 0
    # reconciliation against real timers: overshoot stays ~0
    assert snap["overshoot_s"] <= 0.02 * snap["wall_s"] + 0.005
    assert sum(phases.values()) <= snap["wall_s"] + snap["overshoot_s"] \
        + 1e-6
    # the metrics dump embeds the snapshot; the doctor reads it back
    mfile = glob.glob(str(tele / "metrics-worker0-*.json"))
    assert len(mfile) == 1
    doc = json.load(open(mfile[0]))
    assert doc["attribution"]["steps"] == snap["steps"]
    assert "mxtpu_step_phase_seconds_total" in doc["metrics"]
    assert "mxtpu_step_phase_seconds" in doc["metrics"]  # windowed hist
    report = telemetry.doctor_report(str(tele))
    rec = report["ranks"]["worker0"]
    assert rec["steps"] == snap["steps"]
    assert rec["dominant_phase"] in PHASES
    assert rec["hint"] == HINTS[rec["dominant_phase"]]


def test_disabled_telemetry_attributes_nothing():
    telemetry.disable()
    telemetry.reset_attribution()
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    tr = DataParallelTrainer(net, gluon.loss.L2Loss(), "sgd",
                             {"learning_rate": 0.1})
    x = mx.nd.array(np.random.rand(8, 3).astype(np.float32))
    y = mx.nd.array(np.random.rand(8, 4).astype(np.float32))
    for _ in range(3):
        tr.step(x, y)
    tr.flush()
    assert telemetry.attribution().snapshot()["steps"] == 0


# ---------------------------------------------------------------------------
# (c) EWMA anomaly + queue growth — injected clock, deterministic
# ---------------------------------------------------------------------------
def test_ewma_flags_step_time_regression(tmp_path):
    telemetry.enable(str(tmp_path), rank=0, role="worker")
    clock = [0.0]
    attr = StepAttribution(ring_every=1000, anomaly_factor=3.0,
                           warmup=10, now=lambda: clock[0])
    step = 0
    for _ in range(30):            # steady 10ms baseline
        step += 1
        attr.on_step(step)
        clock[0] += 0.010
    step += 1
    attr.on_step(step)             # closes a normal window
    clock[0] += 0.200              # the regression: one 200ms step
    step += 1
    attr.on_step(step)             # closes the slow window -> flagged
    snap = attr.snapshot()
    assert snap["anomalies"] == 1
    ring = glob.glob(str(tmp_path / "*.mxring"))[0]
    _, events = flight.read_ring(ring)
    (anom,) = [e for e in events if e["kind"] == "perf.anomaly"]
    assert anom["wall_s"] == pytest.approx(0.200)
    assert anom["ewma_s"] < 0.02


def test_queue_growth_flagged(tmp_path):
    telemetry.enable(str(tmp_path), rank=0, role="worker")
    attr = StepAttribution(ring_every=1000)
    for _ in range(300):
        attr.note_queue_depth("io.pipeline", 2)
    for _ in range(60):            # the queue starts rotting
        attr.note_queue_depth("io.pipeline", 12)
    assert attr.snapshot()["queue_growth_events"] >= 1
    ring = glob.glob(str(tmp_path / "*.mxring"))[0]
    _, events = flight.read_ring(ring)
    growth = [e for e in events if e["kind"] == "perf.queue_growth"]
    assert growth and growth[0]["queue"] == "io.pipeline"


# ---------------------------------------------------------------------------
# (d) straggler detector unit
# ---------------------------------------------------------------------------
def test_straggler_detector_flags_slow_rank(tmp_path):
    telemetry.enable(str(tmp_path), rank=None, role="server")
    clock = [0]

    def now_ns():
        return clock[0]

    det = StragglerDetector(factor=2.0, min_samples=5, cooldown_s=100.0,
                            now_ns=now_ns)
    emitted = []
    # rank 0 steps every 10ms, rank 1 every 50ms; beats every 100ms
    for beat in range(1, 12):
        clock[0] = beat * 100_000_000
        emitted += det.observe(0, beat * 10, phase="dispatch")
        emitted += det.observe(1, beat * 2, phase="input_wait")
    assert emitted, "straggler never flagged"
    ev = emitted[0]
    assert ev["rank"] == 1
    assert ev["phase"] == "input_wait"
    assert ev["lag"] >= 2.0
    # cooldown: the persistent skew emitted exactly once
    assert len(det.events) == 1
    snap = det.snapshot()
    assert snap["stragglers"] == ["1"]
    assert snap["rank_step_p50_s"]["1"] == pytest.approx(0.05)
    # the event reached the flight ring
    ring = glob.glob(str(tmp_path / "*.mxring"))[0]
    _, events = flight.read_ring(ring)
    assert any(e["kind"] == "perf.straggler" and e["rank"] == 1
               for e in events)


def test_straggler_detector_prefers_self_measured_p50(tmp_path):
    """A beat carrying the worker's own step-p50 drives the verdict
    directly — no arrival-delta derivation, no real clock: the path
    the 2-worker e2e run rides (p50_fn=telemetry.step_p50_or_none),
    deterministic under arbitrary beat scheduling."""
    telemetry.enable(str(tmp_path), rank=None, role="server")
    clock = [0]
    det = StragglerDetector(factor=2.0, min_samples=4, cooldown_s=100.0,
                            now_ns=lambda: clock[0])
    emitted = []
    for beat in range(1, 8):
        # beats arrive at WILDLY skewed times (what a loaded host does)
        clock[0] = beat * beat * 997_000_000
        emitted += det.observe(0, beat * 3, phase="dispatch",
                               p50_s=0.01)
        emitted += det.observe(1, beat * 3, phase="input_wait",
                               p50_s=0.25)
    assert emitted and all(e["rank"] == 1 for e in emitted)
    assert emitted[0]["phase"] == "input_wait"
    assert emitted[0]["p50_s"] == pytest.approx(0.25)
    assert emitted[0]["lag"] >= 2.0
    snap = det.snapshot()
    assert snap["stragglers"] == ["1"]
    assert snap["rank_step_p50_s"] == {"0": 0.01, "1": 0.25}
    # below min_samples steps the self-report is ignored: no verdict
    # from a warmup-only clock
    det2 = StragglerDetector(factor=2.0, min_samples=4,
                             now_ns=lambda: clock[0])
    assert det2.observe(0, 2, p50_s=0.01) == []
    assert det2.observe(1, 2, p50_s=0.25) == []
    assert det2.snapshot()["rank_step_p50_s"] == {}


def test_straggler_min_gap_floor_suppresses_ratio_only_skew(tmp_path):
    """min_gap_s: a large p50 RATIO over a tiny ABSOLUTE gap (scheduler
    jitter on millisecond steps) stays quiet; a real gap emits even at
    a modest ratio.  The knob the 2-worker e2e rides."""
    clock = [0]
    det = StragglerDetector(factor=2.0, min_samples=4, cooldown_s=100.0,
                            min_gap_s=0.05, now_ns=lambda: clock[0])
    emitted = []
    for beat in range(1, 8):
        clock[0] = beat * 100_000_000
        # 2.7x ratio, 5ms gap: contention noise, not a straggler
        emitted += det.observe(0, beat * 3, p50_s=0.003)
        emitted += det.observe(1, beat * 3, p50_s=0.008)
    assert emitted == []
    # 3x ratio but a 200ms gap: a real fault, emitted (the straggler's
    # new p50 lands first so the transition beat is self-consistent)
    for beat in range(8, 15):
        clock[0] = beat * 100_000_000
        emitted += det.observe(1, beat * 3, p50_s=0.3)
        emitted += det.observe(0, beat * 3, p50_s=0.1)
    assert emitted and all(e["rank"] == 1 for e in emitted)


def test_straggler_reemits_on_dominant_phase_change(tmp_path):
    """A flagged rank whose reported dominant phase MOVES re-emits
    inside the cooldown: the warmup window's jit compile giving way to
    input wait must not be silenced for cooldown_s, or the one emitted
    event names the wrong knob (the e2e flake this pins)."""
    telemetry.enable(str(tmp_path), rank=None, role="server")
    clock = [0]
    det = StragglerDetector(factor=2.0, min_samples=4, cooldown_s=100.0,
                            now_ns=lambda: clock[0])
    emitted = []
    for beat in range(1, 8):
        clock[0] = beat * 100_000_000
        # early beats: the straggler's window is still compile-dominated
        phase = "compute" if beat < 5 else "input_wait"
        emitted += det.observe(0, beat * 3, phase="dispatch", p50_s=0.01)
        emitted += det.observe(1, beat * 3, phase=phase, p50_s=0.25)
    assert [e["phase"] for e in emitted] == ["compute", "input_wait"]
    assert all(e["rank"] == 1 for e in emitted)
    # steady phase afterwards: the cooldown suppresses as before
    clock[0] += 100_000_000
    assert det.observe(1, 30, phase="input_wait", p50_s=0.25) == []


def test_step_p50_or_none_reports_injected_clock(tmp_path):
    """step_p50_or_none: None when disarmed or stepless; the measured
    per-step wall (injected clock) once steps completed."""
    from mxnet_tpu.telemetry.attribution import step_p50_or_none
    assert step_p50_or_none() is None    # telemetry disarmed
    telemetry.enable(str(tmp_path), rank=0, role="worker")
    try:
        clock = [0.0]
        attr = StepAttribution(now=lambda: clock[0])
        telemetry.attribution_mod._ATTR = attr
        assert step_p50_or_none() is None    # armed, no steps yet
        for step in range(1, 7):
            attr.on_step(step)
            clock[0] += 0.04
        attr.flush_window()
        assert step_p50_or_none() == pytest.approx(0.04)
    finally:
        telemetry.disable()
        telemetry.reset_attribution()


def test_straggler_detector_balanced_ranks_quiet():
    det = StragglerDetector(factor=2.0, min_samples=5)
    t0 = time.perf_counter_ns()
    for beat in range(1, 12):
        t = t0 + beat * 100_000_000
        det.observe(0, beat * 10, t_ns=t)
        det.observe(1, beat * 10, t_ns=t)
    assert det.events == []
    assert det.snapshot()["stragglers"] == []


# ---------------------------------------------------------------------------
# (e) doctor from rings alone (the SIGKILLed-rank path)
# ---------------------------------------------------------------------------
def test_doctor_reads_ring_windows_without_metrics_dump(tmp_path):
    telemetry.enable(str(tmp_path), rank=3, role="worker")
    clock = [0.0]
    attr = StepAttribution(ring_every=5, now=lambda: clock[0])
    for step in range(1, 11):
        attr.on_step(step)
        attr.add_phase("collective_or_ps", 0.008)
        clock[0] += 0.010
    attr.flush_window()
    telemetry.disable()   # close the ring like a dead process would not —
    # read_ring works either way; no metrics dump was ever written
    report = telemetry.doctor_report(str(tmp_path))
    rec = report["ranks"]["worker3"]
    assert rec["from_ring"]
    assert rec["steps"] == 10
    assert rec["dominant_phase"] == "collective_or_ps"
    assert "max_staleness" in rec["hint"]
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "doctor",
         str(tmp_path)], capture_output=True, text=True, timeout=120,
        env=_cpu_env(), cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "worker3" in out.stdout
    assert "collective_or_ps" in out.stdout
    assert "max_staleness" in out.stdout
    # --json round-trips
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "doctor",
         str(tmp_path), "--json"], capture_output=True, text=True,
        timeout=120, env=_cpu_env(), cwd=_ROOT)
    assert out.returncode == 0
    assert json.loads(out.stdout)["ranks"]["worker3"]["steps"] == 10


def test_doctor_empty_dir_exits_nonzero(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "doctor",
         str(tmp_path)], capture_output=True, text=True, timeout=120,
        env=_cpu_env(), cwd=_ROOT)
    assert out.returncode == 1


# ---------------------------------------------------------------------------
# (f) the headline: 2-worker run, chaos delay at pipeline.dispatch on rank 1
# ---------------------------------------------------------------------------
_SERVER_SRC = (
    "from mxnet_tpu.kvstore_server import _init_kvstore_server_module\n"
    "_init_kvstore_server_module()\n")

_WORKER_SRC = """\
import os, sys
import numpy as np
port, outdir, rank, epochs, rec, idx = (
    int(sys.argv[1]), sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5], sys.argv[6])
import mxnet_tpu as mx
from mxnet_tpu import gluon, kvstore_ps, telemetry
from mxnet_tpu.io.pipeline import ImagePipelineIter
from mxnet_tpu.parallel import DataParallelTrainer
from mxnet_tpu.resilience import chaos
telemetry.maybe_enable_from_env()
chaos.install_from_env()
mx.random.seed(5)
np.random.seed(5)
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(16, activation='relu'))
net.add(gluon.nn.Dense(24))
net.initialize(mx.init.Xavier())
trainer = DataParallelTrainer(
    net, gluon.loss.SoftmaxCrossEntropyLoss(), 'sgd',
    {'learning_rate': 0.05})
cli = kvstore_ps.PSClient('127.0.0.1', port, rank=rank,
                          connect_retry_s=120)
cli.start_heartbeat(0.03, step_fn=lambda: trainer._step_count,
                    phase_fn=telemetry.dominant_phase_or_none,
                    p50_fn=telemetry.step_p50_or_none)
it = ImagePipelineIter(num_workers=1, seed=7, shuffle=False,
                       path_imgrec=rec, path_imgidx=idx, batch_size=4,
                       data_shape=(3, 28, 28), native_decode=False,
                       prefetch_buffer=1)
# prefetch_buffer=1: each dispatch (and any chaos delay at it) runs
# synchronously in the consumer's input path, so a delayed rank's
# measured step p50 stays slow for the WHOLE run instead of the
# prefetch queue absorbing the delays into one burst step — the
# straggler verdict is then timing-independent
try:
    trainer.fit(it, num_epoch=epochs)
finally:
    it.close()
import time as _t
_t.sleep(0.3)   # a few post-run beats so the server sees final clocks
cli.close()
print('DONE', trainer._step_count, flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_rec(tmp_path, n=24, size=32):
    from mxnet_tpu import recordio
    rng = np.random.RandomState(0)
    rec = str(tmp_path / "p.rec")
    idx = str(tmp_path / "p.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, quality=95))
    w.close()
    return rec, idx


def _run_fleet(tmp_path, tag, epochs, rank1_chaos):
    tele = str(tmp_path / ("tele_" + tag))
    os.makedirs(tele)
    rec, idx = _make_rec(tmp_path)
    port = _free_port()
    # min-gap 50ms: on a 1-core CI host the two workers time-slice, and
    # scheduler jitter on a ~3ms step yields 2-3x p50 RATIOS with no
    # fault anywhere (a few ms of absolute skew); the injected fault's
    # gap is ~200ms/step, so the absolute floor separates signal from
    # noise where no ratio can — host load also shrinks the fault's
    # ratio (the 0.2s delay is additive over an inflating base)
    senv = _cpu_env(DMLC_ROLE="server", MXTPU_PS_PORT=port,
                    MXTPU_HEARTBEAT_TIMEOUT_S=120,
                    MXTPU_STRAGGLER_MIN_SAMPLES=4,
                    MXTPU_STRAGGLER_MIN_GAP_S=0.05,
                    MXTPU_TELEMETRY_DIR=tele)
    server = subprocess.Popen([sys.executable, "-c", _SERVER_SRC],
                              env=senv, stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
    workers = []
    try:
        for rank in (0, 1):
            env = _cpu_env(MXTPU_TELEMETRY_DIR=tele, DMLC_WORKER_ID=rank)
            if rank == 1 and rank1_chaos:
                env["MXTPU_CHAOS"] = rank1_chaos
            workers.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER_SRC, str(port), tele,
                 str(rank), str(epochs), rec, idx],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        for rank, w in enumerate(workers):
            wout, werr = w.communicate(timeout=420)
            assert w.returncode == 0, "rank %d: %s" % (rank, werr[-3000:])
            assert "DONE" in wout
    finally:
        for w in workers:
            w.kill()
        server.send_signal(signal.SIGTERM)
        try:
            server.wait(timeout=30)
        except subprocess.TimeoutExpired:
            server.kill()
    return tele


@pytest.mark.skipif(not pipeline_available(),
                    reason="no multiprocessing shared memory")
def test_two_worker_straggler_doctor_end_to_end(tmp_path):
    """The ISSUE-10 acceptance test.  A seeded 2-worker run (each rank
    training through an ImagePipelineIter + heartbeating its step clock
    and dominant phase to a standalone PS) with chaos `delay` faults at
    pipeline.dispatch on rank 1:

    - the doctor names input_wait as rank 1's dominant phase with its
      knob hint;
    - rank 1 is in the doctor's straggler list AND the server-side
      detector recorded a perf.straggler event naming rank 1 and
      input_wait;
    - per-rank phase sums reconcile with measured wall time within the
      documented tolerance;
    - the same run with no fault reports balanced ranks.
    """
    pytest.importorskip("cv2")
    # one delay per dispatched batch: 6 batches/epoch x 6 epochs = 36
    spec = ",".join("pipeline.dispatch:%d:delay:0.2" % i
                    for i in range(1, 41))
    tele = _run_fleet(tmp_path, "chaos", epochs=6, rank1_chaos=spec)

    report = telemetry.doctor_report(tele)
    r0, r1 = report["ranks"]["worker0"], report["ranks"]["worker1"]
    assert r0["steps"] == r1["steps"] == 36
    # (1) dominant phase on the slowed rank is input_wait, with its hint
    assert r1["dominant_phase"] == "input_wait", r1
    assert "preprocess_threads" in r1["hint"]
    # (2a) offline straggler verdict
    assert report["stragglers"] == ["worker1"], report["stragglers"]
    assert not report["balanced"]
    # (2b) the ONLINE detector flagged rank 1 into the server's ring,
    # naming the dominant phase the rank's heartbeats reported
    stragglers = report["events"]["straggler"]
    assert stragglers, "server never emitted perf.straggler"
    assert all(e["rank"] == 1 for e in stragglers)
    assert any(e["phase"] == "input_wait" for e in stragglers), stragglers
    assert all(e["seen_by"] == "server" for e in stragglers)
    # (3) reconciliation on both ranks: overshoot ~0, phases fit inside
    # the measured wall (documented tolerance: 2% + 5ms timer overhead)
    for rec in (r0, r1):
        psum = sum(rec["phases_s"].values())
        assert psum <= rec["wall_s"] * 1.02 + 0.005
        assert rec["unattributed_s"] >= 0
    # rank 1's input wait is a leading share of its wall; rank 0's is
    # not (0.35 floor, not 0.5: host contention inflates the slowed
    # rank's compute share, and the dominant-phase assertion above
    # already pins input_wait as the largest); the contrast between the
    # ranks is the load-proof signal
    assert r1["phases_s"]["input_wait"] > 0.35 * r1["wall_s"]
    assert r0["phases_s"]["input_wait"] < 0.5 * r0["wall_s"]
    assert r0["phases_s"]["input_wait"] / r0["wall_s"] \
        < r1["phases_s"]["input_wait"] / r1["wall_s"]
    # the CLI tells the same story
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.telemetry", "doctor", tele],
        capture_output=True, text=True, timeout=120, env=_cpu_env(),
        cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "STRAGGLERS" in out.stdout and "worker1" in out.stdout
    assert "input_wait" in out.stdout
    assert "preprocess_threads" in out.stdout

    # (4) the identical run with no fault: balanced ranks
    tele2 = _run_fleet(tmp_path, "clean", epochs=3, rank1_chaos=None)
    report2 = telemetry.doctor_report(tele2)
    assert report2["stragglers"] == []
    assert report2["events"]["straggler"] == []
    assert report2["balanced"]
