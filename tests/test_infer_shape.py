"""Symbol shape/type inference (reference:
tests/python/unittest/test_infer_shape.py + infer_graph_attr_pass.cc).

The executor's bind path must derive every argument/output shape from the
data shape alone for each frontend layer family, reject inconsistent
bindings, and honor the channels-last layouts added round 3.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _infer(sym, **shapes):
    arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**shapes)
    args = dict(zip(sym.list_arguments(), arg_shapes or []))
    auxs = dict(zip(sym.list_auxiliary_states(), aux_shapes or []))
    return args, out_shapes, auxs


def test_mlp_chain():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    args, outs, _ = _infer(out, data=(16, 100))
    assert args["fc1_weight"] == (32, 100)
    assert args["fc1_bias"] == (32,)
    assert args["fc2_weight"] == (10, 32)
    assert outs == [(16, 10)]


def test_conv_chain_nchw():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           stride=(2, 2), name="c")
    p = mx.sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    args, outs, _ = _infer(p, data=(4, 3, 32, 32))
    assert args["c_weight"] == (8, 3, 3, 3)
    assert outs == [(4, 8, 8, 8)]


def test_conv_chain_nhwc():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           layout="NHWC", name="c")
    b = mx.sym.BatchNorm(c, axis=3, name="bn")
    args, outs, auxs = _infer(b, data=(4, 32, 32, 3))
    # channels-last weight layout (O, kh, kw, I)
    assert args["c_weight"] == (8, 3, 3, 3)
    assert args["bn_gamma"] == (8,)
    assert auxs["bn_moving_mean"] == (8,)
    assert outs[0] == (4, 32, 32, 8)


def test_grouped_and_dilated_conv():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, num_group=2,
                           dilate=(2, 2), name="c")
    args, outs, _ = _infer(c, data=(1, 4, 16, 16))
    assert args["c_weight"] == (8, 2, 3, 3)   # I/group = 2
    assert outs == [(1, 8, 12, 12)]           # eff kernel 5


def test_deconv_shape():
    data = mx.sym.Variable("data")
    d = mx.sym.Deconvolution(data, kernel=(4, 4), num_filter=2,
                             stride=(2, 2), pad=(1, 1), name="d")
    args, outs, _ = _infer(d, data=(1, 3, 8, 8))
    assert args["d_weight"] == (3, 2, 4, 4)
    assert outs == [(1, 2, 16, 16)]


def test_rnn_param_vector():
    data = mx.sym.Variable("data")
    r = mx.sym.RNN(data, state_size=16, num_layers=1, mode="lstm",
                   name="rnn")
    args, outs, _ = _infer(r, data=(10, 4, 8))  # (T, B, input)
    # lstm: 4 gates x (16x8 + 16x16 + 16 + 16)
    assert args["rnn_parameters"] == (4 * (16 * 8 + 16 * 16 + 2 * 16),)


def test_embedding_and_flatten():
    data = mx.sym.Variable("data")
    e = mx.sym.Embedding(data, input_dim=50, output_dim=8, name="emb")
    f = mx.sym.Flatten(e)
    args, outs, _ = _infer(f, data=(4, 7))
    assert args["emb_weight"] == (50, 8)
    assert outs == [(4, 56)]


def test_concat_and_broadcast():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = mx.sym.Concat(a, b, dim=1)
    _, outs, _ = _infer(c, a=(2, 3), b=(2, 5))
    assert outs == [(2, 8)]
    s = mx.sym.broadcast_add(a, b)
    _, outs2, _ = _infer(s, a=(2, 1), b=(1, 5))
    assert outs2 == [(2, 5)]


def test_reshape_special_codes():
    data = mx.sym.Variable("data")
    r = mx.sym.Reshape(data, shape=(0, -1))
    _, outs, _ = _infer(r, data=(4, 3, 5))
    assert outs == [(4, 15)]
    r2 = mx.sym.Reshape(data, shape=(-3, 0))
    _, outs2, _ = _infer(r2, data=(4, 3, 5))
    assert outs2 == [(12, 5)]


def test_label_shape_inferred_for_output_heads():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=10, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    args, _, _ = _infer(out, data=(8, 20))
    assert args["softmax_label"] == (8,)


def test_multi_output_heads_group():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=6, name="fc")
    g = mx.sym.Group([mx.sym.softmax(fc), mx.sym.sum(fc)])
    _, outs, _ = _infer(g, data=(4, 3))
    assert outs[0] == (4, 6) and outs[1] == ()


def test_pooling_full_convention():
    data = mx.sym.Variable("data")
    p = mx.sym.Pooling(data, kernel=(3, 3), stride=(2, 2),
                       pooling_convention="full", pool_type="max")
    _, outs, _ = _infer(p, data=(1, 1, 7, 7))
    # ceil((7-3)/2)+1 = 3
    assert outs == [(1, 1, 3, 3)]


def test_simple_bind_rejects_unresolvable():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("mystery")
    out = data + w  # no shape rule relates mystery to data beyond broadcast
    fc = mx.sym.FullyConnected(out, num_hidden=4, name="fc")
    with pytest.raises(mx.MXNetError):
        from mxnet_tpu.executor import Executor
        Executor.simple_bind(mx.sym.SoftmaxOutput(fc, name="softmax"),
                             shapes={})  # no data shape given at all


def test_infer_type_propagates():
    data = mx.sym.Variable("data")
    c = mx.sym.Cast(data, dtype="float64")
    arg_types, out_types, _ = c.infer_type(data="float32")
    assert out_types == [np.dtype("float64")]
